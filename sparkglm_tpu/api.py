"""Formula-driven user API: ``lm()`` / ``glm()`` / ``predict()``.

Mirrors the reference's R front-end — ``sparkLM.formula``
(/root/reference/R/pkg/R/LM.R:24-44): parse formula -> NA-omit -> build model
matrices -> fit -> wrap — with keyword arguments replacing the reference's
16 ``GLM.fit`` overloads (GLM.scala:597-995) and with the intercept flag
actually honoured (the reference computes it and drops it, R/pkg/R/utils.R:19
vs LM.R:37-38).
"""

from __future__ import annotations

import time

import numpy as np

from .config import DEFAULT, NumericConfig
from .data.formula import parse_formula
from .data.frame import as_columns, is_categorical, omit_na
from .data.model_matrix import (build_terms, transform, transform_structured,
                                wants_structured)
from .models import glm as glm_mod
from .models import lm as lm_mod


def _subset_extra(v, keep: np.ndarray, what: str) -> np.ndarray:
    """Align an array-valued weights/offset/m argument with the NA-omitted
    rows: it must match the *pre-omit* length and gets the same keep-mask."""
    arr = np.asarray(v)
    if arr.shape != keep.shape:
        raise ValueError(
            f"{what} has length {arr.shape[0] if arr.ndim else 'scalar'}, "
            f"expected {keep.shape[0]} (the pre-NA-omit row count)")
    return arr[keep]


def _used_columns(f, predictors, extra_names) -> list[str]:
    """Every data column the model frame touches — response(s), offset()
    columns, interaction components, by-name weights/offset/m — for the
    NA-omit scan and missing-column checks (shared by the in-memory and
    from-CSV paths)."""
    from .data.formula import component_source
    sources = [component_source(c) for t in predictors for c in t.split(":")]
    return list(dict.fromkeys(
        [f.response]
        + ([f.response2] if f.response2 else [])
        + list(f.offsets)
        + sources
        + [c for c in extra_names if isinstance(c, str)]))


def _col_or_subset(cols, keep, v, what):
    """A by-name extra resolves against the post-NA-omit columns; an array
    gets the keep-mask (it must match the pre-omit length)."""
    if isinstance(v, str):
        return np.asarray(cols[v], np.float64)
    return None if v is None else _subset_extra(v, keep, what)


def _assemble_offset(f, cols, keep, offset):
    """R's offset semantics: formula offset() terms sum with any offset=
    argument (array or column name)."""
    off = _col_or_subset(cols, keep, offset, "offset")
    for oc in f.offsets:
        o = np.asarray(cols[oc], np.float64)
        off = o if off is None else np.asarray(off, np.float64) + o
    return off


def _offset_col_value(f, offset):
    """What travels with the model for predict(): the by-name offset
    columns (formula offset() terms + a str offset= argument), or None when
    any offset was an array (unrecoverable from new data)."""
    if offset is not None and not isinstance(offset, str):
        return None
    names = f.offsets + ((offset,) if isinstance(offset, str) else ())
    if not names:
        return None
    return names[0] if len(names) == 1 else names


def _design(formula: str, data, *, na_omit: bool, dtype, extra_cols=(),
            design: str = "dense"):
    if design not in ("dense", "structured", "auto"):
        raise ValueError(
            f"design must be 'dense', 'structured' or 'auto', got {design!r}")
    f = parse_formula(formula)
    cols = as_columns(data)
    predictors = f.resolve_predictors(list(cols))
    # by-name weights/offset/m columns join the NA-omit scan so a NaN weight
    # drops its row instead of poisoning the weighted Gramian (R model-frame
    # semantics); interaction terms scan their component source columns, and
    # cbind()/offset() formula columns join too
    used = _used_columns(f, predictors, extra_cols)
    missing = [c for c in f.offsets + ((f.response2,) if f.response2 else ())
               if c not in cols]
    if missing:
        raise KeyError(
            f"formula column {missing[0]!r} not found in data columns "
            f"{list(cols)}")
    n_in = len(next(iter(cols.values()))) if cols else 0
    keep = np.ones(n_in, dtype=bool)
    if na_omit:
        cols, keep = omit_na(cols, used)  # omitNA, R/pkg/R/utils.R:24-27
    yraw = cols[f.response]
    if is_categorical(yraw):
        # two-level factor response: first (sorted) level = failure, as in R
        lv = sorted(np.unique(yraw.astype(str)))
        if len(lv) != 2:
            raise ValueError(
                f"categorical response {f.response!r} must have exactly 2 levels, got {lv}")
        y = (yraw.astype(str) == lv[1]).astype(np.float64)
    else:
        y = yraw.astype(np.float64)
    # R's model.matrix coding for '- 1' formulas: first factor keeps all k
    terms = build_terms(cols, predictors, intercept=f.intercept,
                        no_intercept_coding="full_k_first")
    # design="auto" structures the design exactly when a factor main effect
    # is wide enough for the segment-sum Gramian engine to win
    # (model_matrix.wants_structured; ops/factor_gramian.py)
    structured = (design == "structured"
                  or (design == "auto" and wants_structured(terms)))
    X = (transform_structured(cols, terms, dtype=dtype) if structured
         else transform(cols, terms, dtype=dtype))
    # R evaluates transforms IN the model frame, so na.action sees their
    # output: rows where log(x)/I(x^k)/... produced non-finite values are
    # dropped (with a warning) exactly like raw-NA rows.  The scan runs
    # ONLY when the design contains transform components — untransformed
    # formulas keep the loud fit-entry NA/NaN/Inf error for bad raw data
    from .data.formula import parse_component
    has_transform = any(parse_component(c)[0] is not None
                        for comps in terms.design for c in comps)
    # only the dense block can carry transform outputs (level indices are
    # integers by construction), so the structured scan reads the dense leaf
    bad = (~np.isfinite(np.asarray(X.dense) if structured else X).all(axis=1)
           if has_transform else np.zeros(X.shape[0], bool))
    if bad.any():
        if not na_omit:
            raise ValueError(
                f"{int(bad.sum())} rows have non-finite transformed "
                "predictors (e.g. log of a non-positive value); enable "
                "na_omit or clean the column")
        import warnings
        warnings.warn(
            f"{int(bad.sum())} rows dropped: formula transforms produced "
            "non-finite values (R's na.action runs after model-frame "
            "evaluation)", stacklevel=3)
        good = ~bad
        X = X[good]
        y = y[good]
        cols = {k: np.asarray(v)[good] for k, v in cols.items()}
        keep[np.flatnonzero(keep)[bad]] = False
    # training design column means ride the Terms — R's
    # predict(type="terms") centers each term at colMeans(model.matrix).
    # dtype=f64 accumulates without materialising an f64 copy of X.
    import dataclasses as _dc
    terms = _dc.replace(
        terms, col_means=tuple(X.col_means64() if structured
                               else X.mean(axis=0, dtype=np.float64)))
    return f, X, y, terms, cols, keep


def _reject_penalty_args(*, mesh=None, engine="auto", beta0=None,
                         on_iteration=None, checkpoint_every=0,
                         prefetch=0):
    """Thin wrapper over the declarative capability table
    (sparkglm_tpu/capabilities.py) — the single place every refusal is
    declared.  Raises :class:`~sparkglm_tpu.capabilities.CapabilityError`
    (a ValueError) with the pointed reason."""
    from .capabilities import check_penalized
    check_penalized(mesh=mesh, engine=engine, beta0=beta0,
                    on_iteration=on_iteration,
                    checkpoint_every=checkpoint_every, prefetch=prefetch)


def _reject_elastic_args(*, penalty=None, beta0=None, on_iteration=None,
                         resume=False, engine="elastic"):
    """Thin wrapper over capabilities.check_elastic (see
    ``_reject_penalty_args``)."""
    from .capabilities import check_elastic
    check_elastic(penalty=penalty, beta0=beta0, on_iteration=on_iteration,
                  resume=resume, engine=engine)


def _reject_fleet_args(*, engine="auto", penalty=None, design="dense",
                       mesh=None, beta0=None, on_iteration=None,
                       checkpoint_every=0, start=None):
    """Thin wrapper over capabilities.check_fleet (see
    ``_reject_penalty_args``).  Since PR 20 ``engine='sketch'``,
    ``penalty=`` and ``mesh=`` are LEGAL fleet axes; what remains refused
    lives in the capability table."""
    from .capabilities import check_fleet
    check_fleet(engine=engine, penalty=penalty, design=design, mesh=mesh,
                beta0=beta0, on_iteration=on_iteration,
                checkpoint_every=checkpoint_every, start=start)


def lm(formula: str, data, *, weights=None, offset=None,
       na_omit: bool = True, mesh=None,
       singular: str = "drop", engine: str = "auto", design: str = "auto",
       penalty=None, trace=None, metrics=None,
       config: NumericConfig = DEFAULT) -> lm_mod.LMModel:
    """R-style ``lm(formula, data)`` (ref: sparkLM, R/pkg/R/LM.R:24-44).

    Like R, rank-deficient designs drop later aliased columns and report
    NaN coefficients (``singular="error"`` to raise instead).  ``offset``
    (argument or ``offset()`` formula terms) follows R's ``lm`` semantics:
    coefficients solve the y - offset regression, fitted values include
    the offset, R^2/F use the fitted-based moments of summary.lm.

    ``design``: "dense" materializes every one-hot block; "structured"
    carries factor main effects as level-index vectors and assembles the
    Gramian via segment sums (ops/factor_gramian.py); "auto" (default)
    structures exactly when a factor is wide enough to win
    (``model_matrix.WIDE_FACTOR_LEVELS``).  Requires the einsum engine.

    ``penalty=ElasticNet(...)`` fits the elastic-net lambda path instead
    and returns a :class:`~sparkglm_tpu.penalized.PathModel` (glmnet
    semantics — PARITY.md r11); ``penalty=None`` is the exact unpenalized
    fit, bit-identical to before the option existed."""
    f, X, y, terms, cols, keep = _design(formula, data, na_omit=na_omit,
                                         dtype=np.dtype(config.dtype),
                                         extra_cols=(weights, offset),
                                         design=design)
    if f.response2 is not None:
        raise ValueError(
            "cbind() responses are for binomial glm(); lm() fits a single "
            "numeric response")
    weights_arg = weights
    if isinstance(weights, str):
        weights = cols[weights]  # column name, post-NA-omit (same as glm)
    elif weights is not None:
        weights = _subset_extra(weights, keep, "weights")
    off_arr = _assemble_offset(f, cols, keep, offset)
    if penalty is not None:
        _reject_penalty_args(mesh=mesh, engine=engine)
        from .penalized import path as _pen_path
        import dataclasses
        pm = _pen_path.fit_path(
            X, y, family="gaussian", weights=weights, offset=off_arr,
            penalty=penalty, xnames=terms.xnames, yname=f.response,
            has_intercept=f.intercept, kind="lm", trace=trace,
            metrics=metrics, config=config)
        return dataclasses.replace(
            pm, formula=str(f), terms=terms,
            offset_col=_offset_col_value(f, offset),
            weights_col=weights_arg if isinstance(weights_arg, str) else None,
            has_weights=weights_arg is not None)
    model = lm_mod.fit(
        X, y, weights=weights, offset=off_arr, xnames=terms.xnames,
        yname=f.response,
        has_intercept=f.intercept, mesh=mesh, singular=singular,
        engine=engine, trace=trace, metrics=metrics, config=config)
    import dataclasses
    return dataclasses.replace(
        model, formula=str(f), terms=terms,
        offset_col=_offset_col_value(f, offset),
        weights_col=weights_arg if isinstance(weights_arg, str) else None,
        has_weights=weights_arg is not None)


def glm(formula: str, data, *, family="binomial", link=None, weights=None,
        offset=None, m=None, tol: float = 1e-8, max_iter: int = 100,
        criterion: str = "relative", na_omit: bool = True, mesh=None,
        engine: str = "auto", singular: str = "drop", design: str = "auto",
        verbose: bool = False,
        beta0=None, on_iteration=None, checkpoint_every: int = 0,
        penalty=None, trace=None, metrics=None,
        config: NumericConfig = DEFAULT) -> glm_mod.GLMModel:
    """R-style ``glm(formula, data, family, link, ...)``.

    ``offset``/``m`` may be column names in ``data`` or arrays.
    ``beta0`` is R's ``start=`` (warm-start coefficients — e.g. a
    checkpoint); ``on_iteration``/``checkpoint_every`` surface the
    compiled IRLS in segments for checkpoint/resume (models/glm.py).
    ``design`` chooses the design representation ("dense" | "structured" |
    "auto" — see :func:`lm`); structured designs run the segment-sum
    Gramian engine and require ``engine`` to resolve to einsum.

    ``penalty=ElasticNet(...)`` fits the elastic-net lambda path instead
    and returns a :class:`~sparkglm_tpu.penalized.PathModel` (glmnet
    semantics — PARITY.md r11); ``penalty=None`` is the exact unpenalized
    fit, bit-identical to before the option existed."""
    f, X, y, terms, cols, keep = _design(formula, data, na_omit=na_omit,
                                         dtype=np.dtype(config.dtype),
                                         extra_cols=(weights, offset, m),
                                         design=design)

    weights_arg, m_arg = weights, m  # pre-resolution, for the model record
    yname = f.response
    if f.response2 is not None:
        # cbind(successes, failures): y is success counts out of
        # m = successes + failures (R's grouped-binomial response)
        if m is not None:
            raise ValueError(
                "cbind(successes, failures) already defines the group sizes; "
                "drop the m= argument")
        m = (np.asarray(cols[f.response], np.float64)
             + np.asarray(cols[f.response2], np.float64))
        yname = f"cbind({f.response}, {f.response2})"

    off_arr = _assemble_offset(f, cols, keep, offset)
    if penalty is not None:
        _reject_penalty_args(mesh=mesh, engine=engine, beta0=beta0,
                             on_iteration=on_iteration,
                             checkpoint_every=checkpoint_every)
        from .penalized import path as _pen_path
        import dataclasses
        pm = _pen_path.fit_path(
            X, y, family=family, link=link,
            weights=_col_or_subset(cols, keep, weights, "weights"),
            offset=off_arr,
            m=(m if f.response2 is not None
               else _col_or_subset(cols, keep, m, "m")),
            penalty=penalty, xnames=terms.xnames, yname=yname,
            has_intercept=f.intercept, kind="glm", verbose=verbose,
            trace=trace, metrics=metrics, config=config)
        return dataclasses.replace(
            pm, formula=str(f), terms=terms,
            offset_col=_offset_col_value(f, offset),
            weights_col=weights_arg if isinstance(weights_arg, str) else None,
            m_col=m_arg if isinstance(m_arg, str) else None,
            has_weights=weights_arg is not None,
            has_m=m_arg is not None)
    model = glm_mod.fit(
        X, y, family=family, link=link,
        weights=_col_or_subset(cols, keep, weights, "weights"),
        offset=off_arr,
        m=m if f.response2 is not None else _col_or_subset(cols, keep, m, "m"),
        tol=tol,
        max_iter=max_iter, criterion=criterion, xnames=terms.xnames,
        yname=yname, has_intercept=f.intercept, mesh=mesh,
        engine=engine, singular=singular, verbose=verbose,
        beta0=beta0, on_iteration=on_iteration,
        checkpoint_every=checkpoint_every, trace=trace, metrics=metrics,
        config=config)
    import dataclasses
    return dataclasses.replace(
        model, formula=str(f), terms=terms,
        offset_col=_offset_col_value(f, offset),
        weights_col=weights_arg if isinstance(weights_arg, str) else None,
        m_col=m_arg if isinstance(m_arg, str) else None,
        has_weights=weights_arg is not None,
        # cbind() group sizes travel with the formula itself, not m=
        has_m=m_arg is not None)


def glm_fleet(formula: str, data, *, groups, family="binomial", link=None,
              tau=None, smoothing=None,
              weights=None, offset=None, tol: float = 1e-8,
              max_iter: int = 100, criterion: str = "relative",
              na_omit: bool = True, batch: str = "exact",
              bucket: int | None = None, sort: bool = True,
              start=None,
              verbose: bool = False, trace=None, metrics=None,
              engine: str = "auto", penalty=None, design: str = "dense",
              mesh=None, beta0=None, on_iteration=None,
              checkpoint_every: int = 0, ingest_workers: int = 0,
              config: NumericConfig = DEFAULT):
    """One GLM per group of a long-format frame, fitted as a FLEET — a
    single compiled kernel call for every model (fleet/fitting.py).

    ``data`` may also be a file path (CSV/Parquet/NDJSON) or a list of
    same-schema paths: only the columns the formula + ``groups`` touch
    are read, with ``ingest_workers=N`` fanning the chunk reads across N
    OS processes (``data/ingest.py``; deterministic reassembly — the
    resident frame is identical at any worker count).

    ``groups`` is the segmentation key: a column name in ``data`` or an
    (n,) array aligned with its rows.  The design is built ONCE on the
    long frame (shared columns, factor coding and NA policy for every
    model — the fleet contract), then rows are split by key, ragged
    groups padded with weight-0 trash rows, and the stack fitted by
    :func:`~sparkglm_tpu.fleet.glm_fit_fleet`.  Returns a
    :class:`~sparkglm_tpu.fleet.FleetModel`; ``fleet["label"]`` is an
    ordinary GLMModel carrying this formula's terms for ``predict``.

    ``batch``/``bucket`` tune the fleet kernel (see fleet/); ``start``
    warm-starts every member from stacked (K, p) coefficients in group
    order — the online refresh path (``sparkglm_tpu/online``).

    Three orthogonal scale axes compose here (PR 20):
    ``penalty=ElasticNet(...)`` fits one elastic-net lambda path per
    group in a single batched kernel call and returns a
    :class:`~sparkglm_tpu.fleet.FleetPathModel`;
    ``mesh=`` shards the MODEL axis over the device mesh (K=thousands in
    one pass — ``sg.make_mesh()``); ``engine="sketch"`` runs the r13
    sketched Gramian per member for wide per-tenant designs (same seed
    semantics as the solo fit; NaN standard errors).  Combinations with
    no implementation (penalty + sketch/mesh, ``engine='elastic'``,
    ``design='structured'``, ``beta0=``/checkpoint hooks) are refused
    through the central capability table
    (:mod:`sparkglm_tpu.capabilities`).

    ``family="quantile", tau=0.99`` fits one conditional-quantile model
    per tenant in the same batched kernel call — the per-tenant p99
    pattern (robustreg/; ``smoothing=`` overrides the epsilon schedule).
    Any robust pseudo-family spec (``"quantile(0.9)"``, ``"huber"``,
    ``"l1"``) also works directly as ``family=``.
    """
    _reject_fleet_args(engine=engine, penalty=penalty, design=design,
                       mesh=mesh, beta0=beta0, on_iteration=on_iteration,
                       checkpoint_every=checkpoint_every, start=start)
    if tau is not None or smoothing is not None:
        if not (isinstance(family, str)
                and family.split("(")[0] in ("quantile", "huber",
                                             "l1", "linf")):
            raise ValueError(
                "tau=/smoothing= parameterize a robust pseudo-family; "
                f"pass family='quantile' (or 'huber'/'l1'/'linf'), got "
                f"family={family!r}")
        from .robustreg.pseudo import quantile_family, robust_family
        if tau is not None:
            if family != "quantile":
                raise ValueError(
                    "tau= only applies to family='quantile' (unparenthesized"
                    " — tau is given once, not twice)")
            family = quantile_family(float(tau), smoothing=smoothing)
        else:
            family = robust_family(family, smoothing=smoothing)
    if _all_paths(data):
        data = _ingest_table(formula, data,
                             extra_names=(groups, weights, offset),
                             ingest_workers=int(ingest_workers))
    elif int(ingest_workers) > 0:
        raise ValueError(
            "ingest_workers= applies when data is a file path (or list "
            "of paths); got resident data")
    f, X, y, terms, cols, keep = _design(formula, data, na_omit=na_omit,
                                         dtype=np.dtype(config.dtype),
                                         extra_cols=(weights, offset),
                                         design="dense")
    if f.response2 is not None:
        raise ValueError(
            "cbind() responses are not supported by glm_fleet yet; pass "
            "proportions with per-row weights instead")
    group_name = groups if isinstance(groups, str) else "group"
    if isinstance(groups, str):
        if groups not in cols:
            raise KeyError(
                f"groups column {groups!r} not found in data columns "
                f"{list(cols)}")
        grp = np.asarray(cols[groups])
    else:
        grp = _subset_extra(np.asarray(groups), keep, "groups")
    w_arr = _col_or_subset(cols, keep, weights, "weights")
    off_arr = _assemble_offset(f, cols, keep, offset)

    from .fleet import fit_many as _fit_many
    fleet = _fit_many(
        y, X, groups=grp, weights=w_arr, offset=off_arr, sort=sort,
        group_name=group_name, family=family, link=link, tol=tol,
        max_iter=max_iter, criterion=criterion, xnames=terms.xnames,
        yname=f.response, has_intercept=f.intercept, batch=batch,
        bucket=bucket, start=start, engine=engine, penalty=penalty,
        mesh=mesh, verbose=verbose, trace=trace,
        metrics=metrics, config=config)
    import dataclasses
    return dataclasses.replace(fleet, formula=str(f), terms=terms)


def online_fleet(formula: str, data, *, groups, family="gaussian",
                 link=None, name: str | None = None,
                 weights=None, offset=None,
                 rho: float = 0.99, window_rows: int = 128,
                 drift_threshold: float = 0.25,
                 reference_chunks: int = 4, window_chunks: int = 4,
                 min_count: int = 8,
                 deviance_tolerance: float = 0.05,
                 rollback_tolerance: float | None = None,
                 watch_chunks: int = 4, jitter: float = 0.0,
                 tol: float = 1e-8, max_iter: int = 100,
                 batch: str = "exact", bucket: int | None = None,
                 trace=None, metrics=None, telemetry=None,
                 journal=None, ingest_workers: int = 0,
                 config: NumericConfig = DEFAULT):
    """Seed a per-group GLM fleet from ``data`` and return an armed
    :class:`~sparkglm_tpu.online.OnlineLoop` — the continuous-learning
    front-end.

    Runs :func:`glm_fleet` on the seed frame, wraps the result as a
    served :class:`~sparkglm_tpu.serve.ModelFamily` (one tenant per
    group, seed fit deployed as version 1), and builds the loop around
    it: feed ``loop.step(tenants, X, y)`` chunks (or ``loop.run(source)``
    over a streaming source) and drifted tenants are refreshed —
    closed-form for gaussian/identity, warm fleet refits otherwise —
    shadow-gated, auto-deployed and regression-watched.  Serve the SAME
    family concurrently via ``loop.family.async_engine()``; deploys land
    through the generation counter, recompile-free.

    Chunks are design-level: ``X`` must carry the seed design's columns
    (``loop.family`` validates width).  ``name`` labels the family
    (defaults to the ``groups`` column name).  The loop knobs (``rho``,
    ``window_rows``, drift/window thresholds, tolerances) are documented
    on :class:`~sparkglm_tpu.online.OnlineLoop`.

    ``telemetry=`` (an :class:`~sparkglm_tpu.obs.Telemetry`) attaches the
    runtime observability plane: cycle events feed its flight-recorder
    ring (a ``drift_detected`` or ``auto_rollback`` dumps a record), the
    drift gauges land in its registry, and the same object can serve the
    family's ``async_engine(telemetry=...)`` so serving and learning
    correlate in one event stream.

    ``journal=`` (a directory path) arms the crash-durable write-ahead
    journal: every chunk is journaled before it is applied and
    ``OnlineLoop.resume(journal_dir)`` rebuilds the loop bit-identically
    after a kill (online/journal.py).
    """
    from .online import OnlineLoop
    from .serve import ModelFamily

    fleet = glm_fleet(formula, data, groups=groups, family=family,
                      link=link, weights=weights, offset=offset, tol=tol,
                      max_iter=max_iter, batch=batch, bucket=bucket,
                      trace=trace, metrics=metrics,
                      ingest_workers=ingest_workers, config=config)
    fam_name = name if name is not None else (
        groups if isinstance(groups, str) else "fleet")
    fam = ModelFamily.from_fleet(
        fleet, fam_name,
        metrics=(metrics if metrics is not None
                 else telemetry.metrics if telemetry is not None else None))
    return OnlineLoop(
        fam, rho=rho, window_rows=window_rows,
        drift_threshold=drift_threshold,
        reference_chunks=reference_chunks, window_chunks=window_chunks,
        min_count=min_count, deviance_tolerance=deviance_tolerance,
        rollback_tolerance=rollback_tolerance, watch_chunks=watch_chunks,
        jitter=jitter, tol=tol, max_iter=max_iter, batch=batch,
        trace=trace, metrics=metrics, telemetry=telemetry,
        journal=journal, config=config)


def _stream_io(path, *, chunk_bytes, native, backend: str = "auto",
               levels: bool = True):
    """Resolve the file-streaming backend: global scans, chunk count, and a
    per-chunk reader sharing one contract (``read(i) -> columns dict``).
    ``levels=False`` skips the categorical level scan — a full extra pass
    over the file whose result the PREDICT flow never uses (scoring
    matchCols is structural via the stored Terms; review r4).
    ``backend="auto"`` dispatches on extension — .parquet/.pq stream
    row-group bands (data/parquet.py), .json/.jsonl/.ndjson stream
    newline-aligned NDJSON byte ranges (data/json.py — the reference's own
    fixture format, testData.scala:10-15), everything else newline-aligned
    CSV byte ranges (data/io.py).

    A LIST/TUPLE of paths streams the files as one dataset: per-file
    scans merge (factor levels union-sorted so every file codes
    consistently), chunk indices concatenate file-by-file, and
    ``read(i)`` dispatches to the owning file — the multi-file sharding
    a ``ShardedSource`` fans across ingest workers."""
    import os

    if isinstance(path, (list, tuple)):
        return _stream_io_multi(path, chunk_bytes=chunk_bytes,
                                native=native, backend=backend,
                                levels=levels)
    if backend not in ("auto", "csv", "json", "parquet"):
        raise ValueError(
            f"backend must be 'auto', 'csv', 'json' or 'parquet', "
            f"got {backend!r}")
    from .data.io import is_gz
    gz = is_gz(path)
    if backend == "auto":
        low = str(path).lower()
        if gz:
            low = low[:-3]  # sniff the inner extension of data.csv.gz etc.
        backend = ("parquet" if low.endswith((".parquet", ".pq"))
                   else "json" if low.endswith((".json", ".jsonl", ".ndjson"))
                   else "csv")
    if gz and backend == "parquet":
        raise ValueError(
            "Parquet compresses pages internally; a gzip'd .parquet file "
            "is not a Spark-readable form — decompress it first")
    if gz:
        # one decompression up front (cached), then the streaming flow runs
        # SPLITTABLE on the plain temp file: chunk counts size from the
        # DECOMPRESSED bytes, keeping the chunk_bytes bounded-memory
        # contract Spark's one-task .gz read cannot offer (review r5 — a
        # 2 GB .gz decompressing to 20 GB must not parse as one chunk)
        from .data.io import gunzipped
        path = gunzipped(path)
    # every reader takes (i, columns=None); ``columns`` prunes the read to
    # the named subset where the format can exploit it (Parquet skips the
    # IO entirely; NDJSON skips column building; CSV must parse the line
    # anyway and ignores it)
    if backend == "json":
        from .data import json as json_io
        schema = json_io.scan_json_schema(path, chunk_bytes=chunk_bytes,
                                          native=native)
        lv = (json_io.scan_json_levels(path, chunk_bytes=chunk_bytes,
                                       schema=schema, native=native)
              if levels else None)
        num_chunks = max(1, -(-os.path.getsize(path) // int(chunk_bytes)))

        def read(i, columns=None):
            sub = (schema if columns is None
                   else {k: v for k, v in schema.items() if k in set(columns)})
            return json_io.read_json(path, shard_index=i,
                                     num_shards=num_chunks, schema=sub,
                                     native=native)
        read.columns = list(schema)
        return lv, num_chunks, read
    if backend == "parquet":
        from .data import parquet as pq_io
        schema = pq_io.scan_parquet_schema(path)
        lv = pq_io.scan_parquet_levels(path, schema=schema) if levels else None
        num_chunks = pq_io.row_group_bands(path, chunk_bytes)

        def read(i, columns=None):
            return pq_io.read_parquet(path, shard_index=i,
                                      num_shards=num_chunks, schema=schema,
                                      columns=columns)
    else:
        from .data import io as csv_io
        # both global scans are memory-bounded (chunked merge) — the whole
        # point of this path is files that do not fit
        schema = csv_io.scan_csv_schema(path, native=native,
                                        chunk_bytes=chunk_bytes)
        lv = (csv_io.scan_csv_levels(path, native=native,
                                     chunk_bytes=chunk_bytes)
              if levels else None)
        num_chunks = max(1, -(-os.path.getsize(path) // int(chunk_bytes)))

        def read(i, columns=None):
            return csv_io.read_csv(path, shard_index=i,
                                   num_shards=num_chunks,
                                   schema=schema, native=native)
    # the schema scan already named every column: callers can resolve a
    # formula against ``read.columns`` without materializing a chunk
    read.columns = list(schema)
    return lv, num_chunks, read


def _stream_io_multi(paths, *, chunk_bytes, native, backend, levels):
    """Multi-file twin of :func:`_stream_io`: one global chunk plan over
    several files of the same schema.  Chunk ``i`` belongs to the file
    whose cumulative chunk range contains it, so the global chunk order
    is file order × within-file order — deterministic, re-iterable, and
    shardable by index (data/ingest.py)."""
    if not paths:
        raise ValueError("need at least one path to stream from")
    subs = [_stream_io(p, chunk_bytes=chunk_bytes, native=native,
                       backend=backend, levels=levels) for p in paths]
    merged = None
    if levels:
        # union-sorted per column: every file codes its factors against
        # the GLOBAL level set, like the single-file global level scan
        pooled: dict = {}
        for lv, _, _ in subs:
            for col, vals in (lv or {}).items():
                pooled.setdefault(col, set()).update(vals)
        merged = {c: sorted(s) for c, s in pooled.items()}
    counts = [nc for _, nc, _ in subs]
    starts = [sum(counts[:j]) for j in range(len(counts))]
    readers = [r for _, _, r in subs]

    def read(i, columns=None):
        i = int(i)
        if not 0 <= i < sum(counts):
            raise IndexError(
                f"chunk {i} out of range [0, {sum(counts)})")
        for j in range(len(counts) - 1, -1, -1):
            if i >= starts[j]:
                return readers[j](i - starts[j], columns)
        raise AssertionError("unreachable")  # pragma: no cover

    cols0 = getattr(readers[0], "columns", None)
    if cols0 is not None:
        read.columns = list(cols0)
    return merged, sum(counts), read


def _data_bytes(path) -> int:
    import os as _os
    paths = path if isinstance(path, (list, tuple)) else [path]
    return sum(_os.path.getsize(p) for p in paths)


def _all_paths(data) -> bool:
    return (_is_path(data)
            or (isinstance(data, (list, tuple)) and len(data) > 0
                and all(_is_path(p) for p in data)))


def _ingest_table(formula, path, *, extra_names=(), ingest_workers=0,
                  chunk_bytes: int = 256 << 20, backend: str = "auto"):
    """Load ONLY the columns a formula (plus ``extra_names``) touches
    from file(s) into one resident column dict — the fleet front-ends'
    long-format ingestion.  Chunk reads fan across ``ingest_workers`` OS
    processes (``data/ingest.py``); reassembly is deterministic chunk
    order, so the concatenated columns are identical at any worker
    count."""
    from .data.ingest import ShardedSource

    f = parse_formula(formula)
    _, num_chunks, read = _stream_io(path, chunk_bytes=chunk_bytes,
                                     native=None, backend=backend,
                                     levels=False)
    names = getattr(read, "columns", None)
    if names is None:
        names = list(read(0))
    predictors = f.resolve_predictors(list(names))
    used = _used_columns(f, predictors, extra_names)
    missing = [c for c in used if c not in names]
    if missing:
        raise KeyError(
            f"column {missing[0]!r} not found in file columns "
            f"{list(names)}")

    def read_cols(i):
        cols = read(i, used)
        return tuple(np.asarray(cols[c]) for c in used)

    src = ShardedSource(num_chunks, read_cols,
                        workers=int(ingest_workers), label="table_ingest")
    parts: list[list] = [[] for _ in used]
    for item in src():
        vals = item() if callable(item) else item
        for buf, v in zip(parts, vals):
            buf.append(v)
    return {c: np.concatenate(buf) for c, buf in zip(used, parts)}


def _csv_stream_design(formula, path, *, named_cols, na_omit, dtype,
                       chunk_bytes, native, backend: str = "auto",
                       design: str = "auto"):
    """Shared plan for the from-file streaming fits: global schema + factor
    levels in one pass each (native C++ loader for CSV; pyarrow row-group
    pruned scans for Parquet), a chunking of the file aligned to its IO
    unit (newline byte ranges / row-group bands), and fitted ``Terms``
    every chunk transforms through.  Returns ``(f, terms, num_chunks,
    extract)`` where ``extract(chunk_index)`` yields the per-chunk
    model-frame pieces.

    ``design="auto"`` emits :class:`StructuredDesign` chunks when a factor
    is wide (the streaming engine's chunk passes segment-sum those blocks);
    ``"dense"`` forces one-hot chunks — the constrained-refit profiles need
    dense column access.
    """
    f = parse_formula(formula)
    for what, v in named_cols.items():
        if v is not None and not isinstance(v, str):
            raise ValueError(
                f"{what} must be a column NAME for from-CSV streaming fits "
                "(arrays cannot align with file chunks)")
    levels, num_chunks, _read_chunk = _stream_io(
        path, chunk_bytes=chunk_bytes, native=native, backend=backend)

    # the formula resolves against the SCHEMA scan's column names when the
    # reader exposes them, so even the chunk-0 probe below prunes its read:
    # a 200-column Parquet file with a 5-column formula never materializes
    # the other 195 (the pruning contract tests/test_ingest.py pins)
    names = getattr(_read_chunk, "columns", None)
    chunk0 = None if names is not None else _read_chunk(0)
    if names is None:
        names = list(chunk0)
    predictors = f.resolve_predictors(list(names))
    # BEFORE build_terms (which would fit a basis from chunk0 alone):
    # poly()/bs()/ns() learn their bases from the FULL column (orthogonal
    # coefficients / knot quantiles), which a streaming fit never holds
    from .data.formula import parse_component as _pc
    from .data.model_matrix import BASIS_FUNCS
    basis_used = [c for t in predictors for c in t.split(":")
                  if _pc(c)[0] in BASIS_FUNCS]
    if basis_used:
        raise ValueError(
            f"{basis_used[0]!r} learns its basis from the FULL column; "
            "from-CSV streaming fits would silently fit a basis from the "
            "first chunk only — precompute the basis columns, or load the "
            "data and fit resident")
    used = _used_columns(f, predictors, named_cols.values())
    missing = [c for c in used if c not in names]
    if missing:
        raise KeyError(
            f"formula column {missing[0]!r} not found in CSV columns "
            f"{list(names)}")
    if chunk0 is None:
        chunk0 = _read_chunk(0, used)
    terms = build_terms(chunk0, predictors, intercept=f.intercept,
                        levels=levels, no_intercept_coding="full_k_first")
    structured = design == "auto" and wants_structured(terms)
    # factor response: success level from the GLOBAL level scan — a chunk
    # holding only one response level must still code consistently
    resp_levels = None
    if f.response in levels:
        lv = levels[f.response]
        if len(lv) != 2:
            raise ValueError(
                f"categorical response {f.response!r} must have exactly 2 "
                f"levels, got {lv}")
        resp_levels = lv

    from .data.formula import parse_component
    has_transform = any(parse_component(c)[0] is not None
                        for comps in terms.design for c in comps)
    warned_transform: list = []

    def extract(i: int):
        # prune the read to the columns the model frame touches (Parquet
        # skips the IO for the rest — the columnar tier's advantage)
        cols = _read_chunk(i, used)
        if na_omit:
            cols, _ = omit_na(cols, used)
        yraw = cols[f.response]
        y = ((yraw.astype(str) == resp_levels[1]).astype(np.float64)
             if resp_levels is not None else yraw.astype(np.float64))
        w = (np.asarray(cols[named_cols["weights"]], np.float64)
             if named_cols.get("weights") else None)
        off = None
        off_names = list(f.offsets)
        if named_cols.get("offset"):
            off_names.append(named_cols["offset"])
        for oc in off_names:
            o = np.asarray(cols[oc], np.float64)
            off = o if off is None else off + o
        if f.response2 is not None:
            # cbind(successes, failures) -> proportions + group-size weights,
            # the same conversion the resident m= path applies
            # (models/glm.py::fit)
            msz = y + np.asarray(cols[f.response2], np.float64)
            y = y / np.maximum(msz, 1e-30)
            w = msz if w is None else w * msz
        X = (transform_structured(cols, terms, dtype=dtype) if structured
             else transform(cols, terms, dtype=dtype))
        if has_transform:
            # same model-frame semantics as _design: na_omit drops rows a
            # transform made non-finite (warned once), else it is an error
            # (a structured chunk's transforms live in the dense leaf)
            bad = ~np.isfinite(np.asarray(X.dense) if structured
                               else X).all(axis=1)
            if bad.any():
                if not na_omit:
                    raise ValueError(
                        f"{int(bad.sum())} rows in chunk {i} have "
                        "non-finite transformed predictors; enable na_omit "
                        "or clean the column")
                if not warned_transform:
                    import warnings
                    warnings.warn(
                        "rows dropped: formula transforms produced "
                        "non-finite values (R's na.action runs after "
                        "model-frame evaluation)", stacklevel=2)
                    warned_transform.append(True)
                good = ~bad
                X, y = X[good], y[good]
                w = None if w is None else w[good]
                off = None if off is None else off[good]
        return X, y, w, off

    return f, terms, num_chunks, extract


def quantreg(formula: str, data, *, tau=0.5, weights=None, offset=None,
             smoothing=None, tol: float = 1e-8, max_iter: int = 100,
             criterion: str = "relative", na_omit: bool = True,
             mesh=None, singular: str = "drop", verbose: bool = False,
             trace=None, metrics=None, config: NumericConfig = DEFAULT):
    """Quantile regression by formula — ``quantreg::rq``'s role, run as
    eps-smoothed IRLS (``robustreg/pseudo.py``; arXiv 1902.06391 style).

    A SCALAR ``tau`` fits one model through :func:`glm` with the
    ``quantile(tau)`` pseudo-family and returns a ``GLMModel`` (identity
    link; ``deviance`` is the exact check loss ``2 sum wt rho_tau(r)``;
    pseudo-SEs — see PARITY.md "Robust pseudo-families").  A SEQUENCE of
    taus fits the whole path on ONE shared design via the batched
    simultaneous-tau kernel (``robustreg/taupath.py``) and returns a
    :class:`~sparkglm_tpu.robustreg.TauPath` — every tau advances through
    the same per-pass data sweep, which is where the >=3x win over
    independent cold fits comes from (benchmarks: ``quantile_tau_path``).

    ``smoothing=Smoothing(eps0, factor, eps_min)`` overrides the
    eps-schedule; coefficients of the smoothed optimum differ from the
    exact (non-smooth) quantile solution by O(eps_min) in well-separated
    designs (documented tolerance in PARITY.md)."""
    from .robustreg.pseudo import quantile_family
    from .robustreg.taupath import quantile_tau_path
    if np.ndim(tau) == 0:
        fam = quantile_family(float(tau), smoothing)
        return glm(formula, data, family=fam, weights=weights,
                   offset=offset, tol=tol, max_iter=max_iter,
                   criterion=criterion, na_omit=na_omit, mesh=mesh,
                   singular=singular, verbose=verbose, trace=trace,
                   metrics=metrics, config=config)
    if mesh is not None or singular != "drop":
        raise ValueError(
            "the tau-path driver supports mesh=None and singular='drop' "
            "only (one shared dense design, batched solve); fit taus "
            "one at a time for other settings")
    return quantile_tau_path(
        formula, data, tau, weights=weights, offset=offset,
        smoothing=smoothing, tol=tol, max_iter=max_iter,
        criterion=criterion, na_omit=na_omit, trace=trace,
        metrics=metrics, verbose=verbose, config=config)


def glm_from_csv(formula: str, path: str, *, family="binomial", link=None,
                 weights=None, offset=None, tol: float = 1e-8,
                 max_iter: int = 100, criterion: str = "relative",
                 na_omit: bool = True, chunk_bytes: int = 256 << 20,
                 mesh=None, cache: str = "auto", parse_cache="auto",
                 verbose: bool = False,
                 beta0=None, on_iteration=None, native: bool | None = None,
                 backend: str = "auto", retry=None, checkpoint=None,
                 resume=False, penalty=None, privacy=None, trace=None,
                 metrics=None,
                 prefetch: int = 0, engine: str = "auto",
                 workers: int | None = None, ingest_workers: int = 0,
                 config: NumericConfig = DEFAULT) -> glm_mod.GLMModel:
    """Fit a GLM by formula straight from a CSV too big to load.

    ``prefetch=N`` (N >= 2) pipelines every streaming pass: a background
    thread parses the next byte ranges while the device computes the
    current chunk (``data/pipeline.py``; host memory bound ≈
    ``prefetch x chunk_bytes``).  Bit-identical to the sequential default.

    ``ingest_workers=N`` (N >= 1) moves chunk parsing into N OS worker
    *processes* (``data/ingest.py``) — the parse itself parallelises
    across cores instead of timeslicing one GIL, with chunks handed back
    through shared-memory rings in deterministic chunk order, so
    accumulation stays bit-identical at any worker count.  ``path`` may
    also be a LIST of files sharing a schema: the files stream as one
    dataset (factor levels union across files) and shard naturally
    across the ingest workers.  Composes with ``prefetch=`` (the thread
    tier keeps the device-transfer overlap; the process tier feeds it).

    The end-to-end out-of-memory path: one global schema scan + one factor
    -level scan (``data/io.py``, C++ loader when built), then the file
    streams through the device in newline-aligned ~``chunk_bytes`` slices
    per IRLS pass (``models/streaming.py``) — with ``cache="auto"`` chunks
    are pinned in accelerator memory after the first pass.  ``weights`` /
    ``offset`` must be column names; ``cbind()`` responses and ``offset()``
    terms work as in :func:`glm`.  The fitted model carries the formula and
    ``Terms``, so :func:`predict` scores new column data directly.

    The reference's closest analogue collects the whole dataset to the
    driver (``dfToDenseMatrix``, utils.scala:42-49) — there is no
    out-of-memory story there at all (SURVEY.md §7 hard part #4).

    Fault tolerance (``robust``): ``retry=`` (a ``RetryPolicy``) re-reads
    chunks that fail transiently mid-pass; ``checkpoint=`` (a path or
    ``CheckpointManager``) persists IRLS state after every iteration and
    ``resume=True`` (or ``resume=path``) continues a preempted fit
    bit-for-bit (``models/streaming.py``).

    ``engine="elastic"`` (or any ``workers=``) routes through the elastic
    shard scheduler (``elastic/``): the file is round-robin partitioned
    into independent shard fits on preemptible in-process workers, the
    shard solutions combine in one shot, and a polishing IRLS pass over
    the surviving data finishes the fit.  ``checkpoint=`` then names the
    shard-checkpoint DIRECTORY, preempted shards resume implicitly, and a
    permanently lost shard degrades the fit gracefully
    (``fit_info["elastic"]["degraded"]``) instead of failing it.

    ``engine="sketch"`` streams the sketched IRLS solver instead of the
    exact Gramian passes (``models/streaming.py``; README "Sketched
    solvers") — opt-in, never auto-selected; incompatible with
    ``penalty=``/``workers=`` and leaves standard errors NaN.
    """
    from .models import streaming

    f, terms, num_chunks, extract = _csv_stream_design(
        formula, path, named_cols={"weights": weights, "offset": offset},
        na_omit=na_omit, dtype=np.dtype(config.dtype),
        chunk_bytes=chunk_bytes, native=native, backend=backend)
    if int(ingest_workers) > 0:
        # the disk cache is OFF under process ingest: forked readers would
        # race its writes, and parallel re-parse is the point of the tier
        parse_cleanup = lambda: None  # noqa: E731
    else:
        # chunks past the HBM budget re-stream every IRLS pass: the
        # parsed-chunk disk tier turns those re-parses into memory-mapped
        # loads
        extract, parse_cleanup = _parse_cache_wrap(
            extract, parse_cache, _data_bytes(path))

    from .data.ingest import ShardedSource
    # workers=0 yields the same lazy thunks the old generator did: when
    # the streaming cache holds a chunk, skipping it costs nothing — no
    # byte-range parse, no transform (models/streaming.py::_materialize)
    source = ShardedSource(num_chunks, extract,
                           workers=int(ingest_workers), label="glm_from_csv")

    yname = (f"cbind({f.response}, {f.response2})"
             if f.response2 is not None else f.response)
    if engine not in ("auto", "elastic", "sketch"):
        raise ValueError(
            f"glm_from_csv supports engine='auto', 'elastic' or 'sketch', "
            f"got {engine!r}")
    if privacy is not None and (engine != "auto" or workers is not None
                                or penalty is not None):
        raise ValueError(
            "privacy= runs on the exact single-controller streaming "
            "driver only (chunks are the clipping boundary); drop "
            "engine=/workers=/penalty=")
    if engine == "elastic" or workers is not None:
        _reject_elastic_args(penalty=penalty, beta0=beta0,
                             on_iteration=on_iteration, resume=resume,
                             engine=engine)
        from .elastic import glm_fit_elastic
        import dataclasses
        try:
            model = glm_fit_elastic(
                source, family=family, link=link,
                workers=(4 if workers is None else workers),
                tol=tol, max_iter=max_iter, criterion=criterion,
                xnames=terms.xnames, yname=yname,
                has_intercept=f.intercept, mesh=mesh, cache=cache,
                verbose=verbose, retry=retry, checkpoint=checkpoint,
                trace=trace, metrics=metrics, prefetch=prefetch,
                config=config)
        finally:
            parse_cleanup()
        return dataclasses.replace(
            model, formula=str(f), terms=terms,
            offset_col=_offset_col_value(f, offset),
            weights_col=weights, has_weights=weights is not None)
    if penalty is not None:
        _reject_penalty_args(mesh=mesh, engine=engine, beta0=beta0,
                             on_iteration=on_iteration,
                             prefetch=prefetch)
        from .penalized import stream as _pen_stream
        import dataclasses
        try:
            pm = _pen_stream.glm_path_streaming(
                source, family=family, link=link, penalty=penalty,
                xnames=terms.xnames, yname=yname,
                has_intercept=f.intercept, verbose=verbose, retry=retry,
                checkpoint=checkpoint, resume=resume,
                trace=trace, metrics=metrics, config=config)
        finally:
            parse_cleanup()
        return dataclasses.replace(
            pm, formula=str(f), terms=terms,
            offset_col=_offset_col_value(f, offset),
            weights_col=weights, has_weights=weights is not None)
    try:
        model = streaming.glm_fit_streaming(
            source, family=family, link=link, tol=tol, max_iter=max_iter,
            criterion=criterion, xnames=terms.xnames, yname=yname,
            has_intercept=f.intercept, mesh=mesh, cache=cache,
            verbose=verbose, beta0=beta0, on_iteration=on_iteration,
            retry=retry, checkpoint=checkpoint, resume=resume,
            engine=("sketch" if engine == "sketch" else "auto"),
            privacy=privacy,
            trace=trace, metrics=metrics, prefetch=prefetch, config=config)
    finally:
        parse_cleanup()
    import dataclasses
    return dataclasses.replace(
        model, formula=str(f), terms=terms,
        offset_col=_offset_col_value(f, offset),
        weights_col=weights, has_weights=weights is not None)


def lm_from_csv(formula: str, path: str, *, weights=None, offset=None,
                na_omit: bool = True, chunk_bytes: int = 256 << 20,
                mesh=None, native: bool | None = None, parse_cache="auto",
                backend: str = "auto", retry=None, checkpoint=None,
                resume=False, penalty=None, privacy=None, trace=None,
                metrics=None,
                prefetch: int = 0, engine: str = "auto",
                workers: int | None = None, ingest_workers: int = 0,
                config: NumericConfig = DEFAULT) -> lm_mod.LMModel:
    """OLS/WLS by formula straight from a CSV too big to load (two
    streaming passes: Gramian accumulation, then the exact host-f64
    residual pass; see :func:`glm_from_csv`).

    ``ingest_workers=N`` parses chunks in N OS worker processes with
    deterministic reassembly, and ``path`` may be a list of same-schema
    files — see :func:`glm_from_csv`.

    ``weights``/``offset`` must be column names; ``offset()`` formula
    terms follow R's ``lm`` semantics like the resident :func:`lm`
    (VERDICT r3 #6 — streaming was the one place lm offset parity ended).

    ``engine="elastic"`` / ``workers=`` shard the fit across preemptible
    workers with exact Gramian-additive combine (see :func:`glm_from_csv`
    and ``elastic/``).
    """
    from .models import streaming

    pre = parse_formula(formula)  # reject before any file IO
    if pre.response2 is not None:
        raise ValueError(
            "cbind() responses are for binomial glm(); lm() fits a single "
            "numeric response")

    f, terms, num_chunks, extract = _csv_stream_design(
        formula, path, named_cols={"weights": weights, "offset": offset},
        na_omit=na_omit, dtype=np.dtype(config.dtype),
        chunk_bytes=chunk_bytes, native=native, backend=backend)
    if int(ingest_workers) > 0:
        # disk cache off under process ingest (see glm_from_csv)
        parse_cleanup = lambda: None  # noqa: E731
    else:
        # lm streams twice (Gramian pass + exact residual pass; three with
        # an offset + intercept): later passes load memory-mapped parsed
        # chunks instead of re-parsing
        extract, parse_cleanup = _parse_cache_wrap(
            extract, parse_cache, _data_bytes(path))

    from .data.ingest import ShardedSource
    source = ShardedSource(num_chunks, extract,
                           workers=int(ingest_workers), label="lm_from_csv")

    if engine == "sketch":
        raise ValueError(
            "lm_from_csv has no sketched solver: OLS/WLS streams the exact "
            "normal equations in two passes and never iterates, so there "
            "is no per-iteration Gramian to sketch — engine='sketch' is a "
            "GLM option (glm_from_csv / glm)")
    if engine not in ("auto", "elastic"):
        raise ValueError(
            f"lm_from_csv supports engine='auto' or engine='elastic', "
            f"got {engine!r}")
    if privacy is not None and (engine != "auto" or workers is not None
                                or penalty is not None):
        raise ValueError(
            "privacy= runs on the exact single-controller streaming "
            "driver only (chunks are the clipping boundary); drop "
            "engine=/workers=/penalty=")
    if engine == "elastic" or workers is not None:
        _reject_elastic_args(penalty=penalty, resume=resume)
        from .elastic import lm_fit_elastic
        import dataclasses
        try:
            model = lm_fit_elastic(
                source, workers=(4 if workers is None else workers),
                xnames=terms.xnames, yname=f.response,
                has_intercept=f.intercept, mesh=mesh, retry=retry,
                checkpoint=checkpoint, trace=trace, metrics=metrics,
                prefetch=prefetch, config=config)
        finally:
            parse_cleanup()
        return dataclasses.replace(
            model, formula=str(f), terms=terms, weights_col=weights,
            offset_col=_offset_col_value(f, offset),
            has_weights=weights is not None)
    if penalty is not None:
        _reject_penalty_args(mesh=mesh, prefetch=prefetch)
        from .penalized import stream as _pen_stream
        import dataclasses
        try:
            pm = _pen_stream.lm_path_streaming(
                source, penalty=penalty, xnames=terms.xnames,
                yname=f.response, has_intercept=f.intercept, retry=retry,
                checkpoint=checkpoint, resume=resume,
                trace=trace, metrics=metrics, config=config)
        finally:
            parse_cleanup()
        return dataclasses.replace(
            pm, formula=str(f), terms=terms, weights_col=weights,
            offset_col=_offset_col_value(f, offset),
            has_weights=weights is not None)
    try:
        model = streaming.lm_fit_streaming(
            source, xnames=terms.xnames, yname=f.response,
            has_intercept=f.intercept, mesh=mesh, retry=retry,
            checkpoint=checkpoint, resume=resume, privacy=privacy,
            trace=trace, metrics=metrics, prefetch=prefetch, config=config)
    finally:
        parse_cleanup()
    import dataclasses
    return dataclasses.replace(model, formula=str(f), terms=terms,
                               weights_col=weights,
                               offset_col=_offset_col_value(f, offset),
                               has_weights=weights is not None)


def glm_from_parquet(formula: str, path: str, **kwargs) -> glm_mod.GLMModel:
    """Fit a GLM by formula straight from a Parquet file too big to load.

    The columnar twin of :func:`glm_from_csv` (SURVEY §2.3's Spark-reader
    role: the reference's DataFrames arrive from any source — testData
    fixtures are JSON, testData.scala:10-15): the same streaming IRLS
    engine, with chunks as row-group BANDS and the schema read from the
    typed footer instead of a data pass (``data/parquet.py``).  Same
    keywords as :func:`glm_from_csv` except ``native`` (the C++ CSV
    loader does not apply); multi-host fits shard by row-group band via
    ``read_parquet(shard_index=process_index(), num_shards=...)``.
    """
    kwargs.pop("native", None)
    return glm_from_csv(formula, path, backend="parquet", **kwargs)


def lm_from_parquet(formula: str, path: str, **kwargs) -> lm_mod.LMModel:
    """OLS/WLS by formula straight from a Parquet file too big to load —
    the columnar twin of :func:`lm_from_csv`; see :func:`glm_from_parquet`."""
    kwargs.pop("native", None)
    return lm_from_csv(formula, path, backend="parquet", **kwargs)


def glm_from_json(formula: str, path: str, **kwargs) -> glm_mod.GLMModel:
    """Fit a GLM by formula straight from a newline-delimited JSON file —
    the reference's own fixture format (Spark ``jsonFile``,
    testData.scala:10-15).  Same streaming engine as
    :func:`glm_from_csv`; records are one JSON object per line, columns
    are the union of keys, parsed by the native C++ loader when built
    (``data/json.py``, native/loader.cpp::sgio_read_json)."""
    return glm_from_csv(formula, path, backend="json", **kwargs)


def lm_from_json(formula: str, path: str, **kwargs) -> lm_mod.LMModel:
    """OLS/WLS by formula straight from a newline-delimited JSON file;
    see :func:`glm_from_json`."""
    return lm_from_csv(formula, path, backend="json", **kwargs)


def _parse_cache_wrap(extract, mode, csv_bytes: int):
    """Disk tier for parsed CSV chunks (VERDICT r2 weak #7): a chunk past
    the HBM budget previously re-paid its byte-range parse + transform on
    EVERY IRLS pass.

    A chunk is persisted on its SECOND extract call — the first call may
    be the only one (the streaming HBM cache pins hot chunks and never
    re-extracts them), so fully-cached datasets write nothing.  Writes
    stop at a byte budget (half the free space of the temp dir, measured
    up front), so an optimistic size estimate can not fill the disk:
    chunks beyond the budget simply keep re-parsing.  ``mode``: "auto"
    enables the tier when the CSV could plausibly fit; True forces it
    (still budgeted); False disables.  Returns (wrapped_extract, cleanup).
    """
    import os
    import shutil
    import tempfile

    try:
        free = shutil.disk_usage(tempfile.gettempdir()).free
    except OSError:
        free = 0
    if mode == "auto":
        # binary f32 design ~ the CSV text size (digits+commas vs 4 bytes);
        # the budget below bounds the damage when this underestimates
        mode = csv_bytes <= free // 2
    if not mode:
        return extract, lambda: None
    tmpdir = tempfile.mkdtemp(prefix="sparkglm_parsed_")
    state = {"budget": free // 2, "seen": set(), "closed": False}

    def cached(i: int):
        base = os.path.join(tmpdir, str(i))
        if os.path.exists(base + ".X.npy"):
            X = np.load(base + ".X.npy", mmap_mode="r")
            y = np.load(base + ".y.npy", mmap_mode="r")
            w = (np.load(base + ".w.npy", mmap_mode="r")
                 if os.path.exists(base + ".w.npy") else None)
            off = (np.load(base + ".off.npy", mmap_mode="r")
                   if os.path.exists(base + ".off.npy") else None)
            return X, y, w, off
        chunk = extract(i)
        if not isinstance(chunk[0], np.ndarray):
            # StructuredDesign chunks skip the disk tier (multi-leaf layout
            # does not fit the per-array .npy scheme); the streaming HBM
            # cache still pins them after the first pass
            return chunk
        if i not in state["seen"]:
            state["seen"].add(i)     # first touch: maybe the only one
            return chunk
        if state["closed"]:
            return chunk
        nbytes = sum(np.asarray(a).nbytes for a in chunk if a is not None)
        if nbytes > state["budget"]:
            state["closed"] = True   # over budget: keep re-parsing the rest
            return chunk
        state["budget"] -= nbytes
        # write-then-rename so a crashed writer never leaves a torn chunk
        for name, arr in zip(("X", "y", "w", "off"), chunk):
            if arr is None:
                continue
            tmp = f"{base}.{name}.tmp.npy"  # np.save appends .npy otherwise
            np.save(tmp, np.asarray(arr))
            os.replace(tmp, f"{base}.{name}.npy")
        return chunk

    def cleanup():
        shutil.rmtree(tmpdir, ignore_errors=True)

    return cached, cleanup


def _is_path(data) -> bool:
    """The R verbs accept the training DATA or the training FILE: a str /
    PathLike routes the refit through the from-CSV streaming path."""
    import os
    return isinstance(data, (str, os.PathLike))


def _carry_fit_arg(model, key: str, current, verb: str):
    """R re-evaluates the original call in its refitting verbs (update,
    drop1, profile): a by-NAME weights/m column recorded on the model
    (weights_col/m_col, like offset_col) is recovered automatically; an
    array-valued one cannot be, so the verb refuses rather than silently
    refitting without it (ADVICE r2)."""
    if current is not None:
        return current
    col = getattr(model, f"{key}_col", None)
    if col is not None:
        return col
    if getattr(model, f"has_{key}", False):
        raise ValueError(
            f"model was fit with an array {key}=; pass {key}= to {verb} "
            f"(or fit with a named {key} column so it travels with the "
            "model)")
    return None


def update(model, formula: str = "~ .", data=None, **overrides):
    """R's ``update(model, formula, data)``: refit with a modified formula.

    ``.`` stands for the corresponding part of the original formula:
    ``"~ . + z"`` adds a term, ``"~ . - x"`` removes one, ``"y2 ~ ."``
    swaps the response, ``"~ . - 1"`` drops the intercept.  The refit
    re-evaluates the original call the way R does: family/link/tol and
    by-NAME weights/offset/m columns travel with the model (a glm.nb
    model re-estimates theta through :func:`glm_nb`); array-valued
    weights/offset/m cannot be recovered from new data, so they must be
    re-passed through ``overrides`` — update refuses to silently drop
    them.  Other fit arguments (engine=, config=, ...) pass through
    ``overrides`` too.

    ``data`` may be the training columns OR a CSV path: a path routes the
    refit through the out-of-core streaming engine (the same
    :func:`glm_from_csv`/:func:`lm_from_csv` path the model came from), so
    the R verbs work on models whose data never fits in memory.
    """
    import re as _re

    from .data.formula import TERM_RE, _expand_term, extract_offset_terms
    from .models.lm import LMModel

    if getattr(model, "formula", None) is None:
        raise ValueError("update needs a formula-fitted model")
    if data is None:
        raise ValueError(
            "pass the training data (models do not retain it): "
            "update(model, '~ . + z', data)")
    old = parse_formula(model.formula)
    if not isinstance(model, LMModel):
        # fail early with a clear message when the refit could not
        # reconstruct the family from its stored name (user-built Family
        # objects); registry + quasi(...)/negative_binomial(...) names pass
        from .families.families import get_family
        try:
            get_family(model.family)
        except ValueError:
            raise ValueError(
                f"update cannot reconstruct family {model.family!r} from "
                "its name; refit explicitly with the Family object") from None
    old_lhs = model.formula.split("~", 1)[0].strip()
    lhs, rhs = (formula.split("~", 1) if "~" in formula else ("", formula))
    lhs = lhs.strip()
    resp = old_lhs if lhs in ("", ".") else lhs

    rhs, added_offsets = extract_offset_terms(rhs, formula)
    offsets = list(old.offsets)
    # a fit-time offset= COLUMN is part of the model being updated — carry
    # it as an offset() term (an array offset cannot be recovered: refuse
    # rather than silently refit unoffset, same rule as predict)
    stored_off = getattr(model, "offset_col", None)
    if isinstance(stored_off, str):
        stored_off = (stored_off,)
    for nm in stored_off or ():
        if nm not in offsets:
            offsets.append(nm)
    if (not stored_off and getattr(model, "has_offset", False)
            and "offset" not in overrides):
        raise ValueError(
            "model was fit with an array offset; pass offset= to update "
            "(or fit with a named offset column)")
    offsets.extend(o for o in added_offsets if o not in offsets)

    # R's update() re-evaluates the original call INCLUDING weights= and
    # m= — a weighted fit must not silently refit unweighted (ADVICE r2)
    for key in ("weights", "m"):
        v = _carry_fit_arg(model, key, overrides.get(key), "update")
        if v is not None:
            overrides[key] = v

    leftover = _re.sub(rf"([+-]?)\s*({TERM_RE})", "", rhs)
    if _re.sub(r"[\s+]", "", leftover):
        raise ValueError(f"unsupported update syntax in {formula!r}")

    terms: list[str] = []
    removals: list[frozenset] = []
    intercept = old.intercept
    for sign, chunk in _re.findall(rf"([+-]?)\s*({TERM_RE})", rhs):
        if chunk == ".":
            terms.extend(t for t in old.predictors if t not in terms)
            continue
        if _re.fullmatch(r"\d+", chunk):
            if chunk == "1":
                intercept = sign != "-"
            elif chunk == "0":
                intercept = False
            else:
                raise ValueError(f"numeric term {chunk!r} in {formula!r}")
            continue
        if sign == "-":
            if "*" in chunk:
                raise ValueError(
                    f"cannot remove a '*' crossing ({chunk!r}); remove the "
                    "individual terms")
            from .data.formula import canonical_component
            removals.append(frozenset(
                canonical_component(c) for c in chunk.split(":")))
            continue
        for term, _ in _expand_term(sign, chunk, formula):
            if term not in terms:
                terms.append(term)
    terms = [t for t in terms if frozenset(t.split(":")) not in removals]
    if not terms and not intercept:
        raise ValueError(f"update {formula!r} removes every term")

    rhs_out = " + ".join(terms + [f"offset({o})" for o in offsets]) or "1"
    new_formula = f"{resp} ~ {rhs_out}" + ("" if intercept else " - 1")

    from .families.families import nb_theta
    if _is_path(data):
        # out-of-core refit straight from the file: the R verbs work on the
        # from-CSV flagship path too (VERDICT r2 missing #4).  weights must
        # already be a column name here (_csv_stream_design enforces it).
        if isinstance(model, LMModel):
            return lm_from_csv(new_formula, str(data), **overrides)
        if nb_theta(model.family) is not None:
            raise ValueError(
                "negative-binomial fits have no from-CSV path yet; load "
                "the data and update in memory")
        if overrides.pop("m", None) is not None:
            raise ValueError(
                "from-CSV updates express group sizes with a "
                "cbind(successes, failures) response, not m=")
        overrides.setdefault("family", model.family)
        overrides.setdefault("link", model.link)
        overrides.setdefault("tol", model.tol)
        return glm_from_csv(new_formula, str(data), **overrides)
    if isinstance(model, LMModel):
        return lm(new_formula, data, **overrides)
    if nb_theta(model.family) is not None:
        overrides.setdefault("link", model.link)
        overrides.setdefault("tol", model.tol)
        return glm_nb(new_formula, data, **overrides)
    overrides.setdefault("family", model.family)
    overrides.setdefault("link", model.link)
    overrides.setdefault("tol", model.tol)
    return glm(new_formula, data, **overrides)


def glm_nb(formula: str, data, *, link: str = "log", weights=None,
           offset=None, theta0: float | None = None, tol: float = 1e-8,
           max_iter: int = 100, criterion: str = "relative",
           na_omit: bool = True, mesh=None, verbose: bool = False,
           config: NumericConfig = DEFAULT, **kw):
    """MASS-style ``glm.nb(formula, data)``: negative binomial regression
    with the shape ``theta`` estimated by maximum likelihood
    (models/negbin.py).  Formula surface matches :func:`glm` (interactions,
    offset() terms, by-name weights); the returned model's family records
    the fitted theta."""
    from .models.negbin import fit_nb

    f, X, y, terms, cols, keep = _design(formula, data, na_omit=na_omit,
                                         dtype=np.dtype(config.dtype),
                                         extra_cols=(weights, offset))
    if f.response2 is not None:
        raise ValueError("cbind() responses are binomial; glm_nb models "
                         "overdispersed counts")

    off_arr = _assemble_offset(f, cols, keep, offset)
    model = fit_nb(
        X, y, link=link, weights=_col_or_subset(cols, keep, weights, "weights"),
        offset=off_arr, theta0=theta0, tol=tol, max_iter=max_iter,
        criterion=criterion, xnames=terms.xnames, yname=f.response,
        has_intercept=f.intercept, mesh=mesh, verbose=verbose,
        config=config, **kw)
    import dataclasses
    return dataclasses.replace(
        model, formula=str(f), terms=terms,
        offset_col=_offset_col_value(f, offset),
        weights_col=weights if isinstance(weights, str) else None,
        has_weights=weights is not None)


def _csv_constrained_dev(model, path: str, *, weights=None, offset=None,
                         m=None, na_omit: bool = True,
                         config: NumericConfig = DEFAULT,
                         chunk_bytes: int = 256 << 20, native=None,
                         mesh=None, cache: str = "auto",
                         parse_cache="auto", **fit_kw):
    """Build ``constrained_dev(j, val)`` for a from-CSV model: drop column
    ``j``, fold ``X[:, j] * val`` into the offset, and refit by streaming
    the file (models/profile.py's out-of-core hook)."""
    from .models import streaming

    weights = _carry_fit_arg(model, "weights", weights, "confint_profile")
    if _carry_fit_arg(model, "m", m, "confint_profile") is not None:
        raise ValueError(
            "from-CSV profiles express group sizes with a "
            "cbind(successes, failures) response, not m=")
    if offset is not None and not isinstance(offset, str):
        raise ValueError(
            "from-CSV profiles need offset as a column name (arrays cannot "
            "align with file chunks)")
    # formula offset() terms stream automatically (extract folds f.offsets);
    # a fit-time offset= NAME is the stored extra; an array one is gone
    f_old = parse_formula(model.formula)
    stored = getattr(model, "offset_col", None)
    stored = (stored,) if isinstance(stored, str) else tuple(stored or ())
    extra_off = [nm for nm in stored if nm not in f_old.offsets]
    if offset is None and not extra_off and not stored \
            and getattr(model, "has_offset", False):
        raise ValueError(
            "model was fit with an array offset; from-CSV profiles need it "
            "as a named column")
    off_name = offset if offset is not None else \
        (extra_off[0] if extra_off else None)

    import os as _os

    f, terms, num_chunks, extract = _csv_stream_design(
        model.formula, path,
        named_cols={"weights": weights, "offset": off_name},
        na_omit=na_omit, dtype=np.dtype(config.dtype),
        chunk_bytes=chunk_bytes, native=native,
        design="dense")  # constrained refits slice X[:, j] — dense only
    if terms.xnames != tuple(model.xnames):
        raise ValueError(
            f"file rebuilds design columns {terms.xnames} but the model "
            f"has {tuple(model.xnames)} — pass the file the model was fit on")
    # dozens of constrained refits stream the same file: parse once
    extract, parse_cleanup = _parse_cache_wrap(
        extract, parse_cache, _os.path.getsize(path))
    p = model.n_params
    aliased = (np.zeros(p, bool) if getattr(model, "aliased", None) is None
               else np.asarray(model.aliased, bool))

    def constrained_dev(j: int, val: float) -> float:
        # aliased columns stay out of the refit, as in the resident walker
        # (keeping them makes every constrained Gramian singular)
        keep = [k for k in range(p) if k != j and not aliased[k]]

        def source():
            for i in range(num_chunks):
                def thunk(i=i):
                    X, y, w, off = extract(i)
                    off2 = X[:, j] * val if off is None else off + X[:, j] * val
                    return X[:, keep], y, w, off2
                yield thunk

        sub = streaming.glm_fit_streaming(
            source, family=model.family, link=model.link, tol=model.tol,
            xnames=tuple(np.asarray(terms.xnames)[keep]),
            yname=model.yname, has_intercept=False, mesh=mesh,
            cache=cache, config=config, **fit_kw)
        return float(sub.deviance)

    constrained_dev.cleanup = parse_cleanup  # caller removes the disk tier
    return constrained_dev


def confint_profile(model, data, *, level: float = 0.95, which=None,
                    weights=None, offset=None, m=None, na_omit: bool = True,
                    config: NumericConfig = DEFAULT, **kw) -> np.ndarray:
    """Profile-likelihood intervals for a formula-fitted GLM (R's default
    ``confint.glm``).  Pass the TRAINING data — the model frame (NA
    omission, response coding, cbind group sizes, offsets) is rebuilt
    through the same ``_design`` path :func:`glm` fit with, and a stored
    by-name fit-time offset is recovered automatically (an array offset
    must be re-passed, as in :func:`predict`).  ``weights``/``offset``/``m``
    accept column names or arrays like :func:`glm`; a non-default
    ``engine=``/``config=`` used at fit time should be re-passed too so
    the constrained refits (and the rebuilt design's dtype) match."""
    from .models.profile import confint_profile as _profile

    if model.terms is None:
        raise ValueError(
            "model was fit from arrays; call "
            "sparkglm_tpu.models.profile.confint_profile(model, X, y, ...) "
            "directly")
    if _is_path(data):
        # out-of-core profile: each constrained refit STREAMS the file
        # (VERDICT r2 missing #4) — expensive (one full-file IRLS per
        # profile point) but exact, and never materializes the design.
        # Walker kwargs stay with the walker; the rest go to the refits.
        max_steps = kw.pop("max_steps", 30)
        dev_fn = _csv_constrained_dev(
            model, str(data), weights=weights, offset=offset, m=m,
            na_omit=na_omit, config=config, **kw)
        try:
            return _profile(model, level=level, which=which,
                            max_steps=max_steps, constrained_dev_fn=dev_fn)
        finally:
            dev_fn.cleanup()
    # stored by-name fit-time weights/m are recovered (or their array
    # originals refused) exactly like update() — profiling a weighted
    # model against unweighted constrained refits would silently produce
    # wrong intervals
    weights = _carry_fit_arg(model, "weights", weights, "confint_profile")
    m = _carry_fit_arg(model, "m", m, "confint_profile")
    # a stored by-name fit-time offset must join the NA-omit scan exactly
    # as it did at fit time (its column was in extra_cols then too)
    stored_off = getattr(model, "offset_col", None) if offset is None else None
    stored_names = ([] if stored_off is None else
                    [stored_off] if isinstance(stored_off, str)
                    else list(stored_off))
    f, X, y, terms, cols, keep = _design(
        model.formula, data, na_omit=na_omit,
        dtype=np.dtype(config.dtype),
        extra_cols=(weights, offset, m, *stored_names))
    if terms.xnames != tuple(model.xnames):
        raise ValueError(
            f"data rebuilds design columns {terms.xnames} but the model has "
            f"{tuple(model.xnames)} — pass the data the model was fit on")

    if f.response2 is not None:
        if m is not None:
            raise ValueError("cbind() already defines group sizes")
        m = y + np.asarray(cols[f.response2], np.float64)
    else:
        m = _col_or_subset(cols, keep, m, "m")

    if offset is None:
        # recover the stored fit-time offset exactly like predict()
        if stored_names:
            off = sum(np.asarray(cols[nm], np.float64)
                      for nm in stored_names)
        elif getattr(model, "has_offset", False):
            raise ValueError(
                "model was fit with an array offset; pass offset= to "
                "confint_profile (or fit with a named offset column)")
        else:
            off = None
    else:
        off = _assemble_offset(f, cols, keep, offset)

    kw.setdefault("config", config)
    return _profile(model, X, y, level=level, which=which,
                    weights=_col_or_subset(cols, keep, weights, "weights"),
                    offset=off, m=m, **kw)


class TermsPrediction:
    """R's ``predict(type="terms")`` payload: per-TERM link-scale
    contributions, each centered at the training design's column means,
    plus the ``constant`` attribute (sum of the centered-away parts —
    rowsums(matrix) + constant = the link-scale prediction)."""

    def __init__(self, matrix: np.ndarray, columns: tuple, constant: float):
        self.matrix = matrix
        self.columns = columns
        self.constant = constant

    def __repr__(self):
        return (f"TermsPrediction(columns={self.columns}, "
                f"constant={self.constant:.6g}, n={self.matrix.shape[0]})")


def _predict_terms(model, X: np.ndarray) -> TermsPrediction:
    """R's predict.lm/glm ``type="terms"``: with an intercept, term columns
    ii give (X[, ii] - colMeans(mm)[ii]) %*% beta[ii] and constant =
    sum(avx*beta); a NO-intercept model is not centered and its constant
    is 0 (R only centers when attr(terms, "intercept") > 0)."""
    from .data.model_matrix import term_spans

    if model.has_intercept:
        avx = np.asarray(model.terms.col_means, np.float64)
        if avx.size != model.n_params:
            raise ValueError(
                "model's Terms carry no training column means — from-CSV "
                "streaming fits do not record them (and models saved "
                "before r3 predate the field), so type='terms' is "
                "unavailable on this model")
    else:
        avx = np.zeros(model.n_params)
    beta = np.nan_to_num(np.asarray(model.coefficients, np.float64))
    spans = term_spans(model.terms)
    Xf = np.asarray(X, np.float64)
    out = np.empty((Xf.shape[0], len(spans)))
    for k, (_, lo, hi) in enumerate(spans):
        out[:, k] = (Xf[:, lo:hi] - avx[lo:hi]) @ beta[lo:hi]
    return TermsPrediction(out, tuple(lbl for lbl, _, _ in spans),
                           float(avx @ beta))


def _fit_time_offset(model, cols):
    """R's ``predict.glm`` scoring contract, shared by :func:`predict` and
    the online serving engine (serve/engine.py): a by-name fit-time offset
    is re-extracted from the new data; an array offset cannot be recovered
    and is refused rather than silently scored without."""
    off_col = getattr(model, "offset_col", None)
    if off_col is not None:
        names = [off_col] if isinstance(off_col, str) else list(off_col)
        missing = [nm for nm in names if nm not in cols]
        if missing:
            raise ValueError(
                f"model was fit with offset column {missing[0]!r}, which is "
                "missing from the new data; pass offset= explicitly to override")
        return sum(np.asarray(cols[nm], np.float64) for nm in names)
    if getattr(model, "has_offset", False):
        raise ValueError(
            "model was fit with an array offset; pass offset= to predict "
            "(or fit with the offset as a named column so it travels with "
            "the model)")
    return None


def _predict_from_path(model, path, *, chunk_bytes: int = 256 << 20,
                       native: bool | None = None, out_path: str | None = None,
                       trace=None, metrics=None, **kwargs):
    """Out-of-core scoring: stream a CSV too big to load through the
    training ``Terms`` + the model's scorer, chunk by chunk (VERDICT r3
    #5 — the reference predicts executor-side on distributed data,
    LM.scala:52-61; this is that role for file-resident data).

    Each byte-range chunk goes through the EXACT resident predict path
    (``predict(model, chunk_cols, **kwargs)``), so results are
    bit-identical to loading the file whole: the transform and the
    X·beta / quadform scorers are row-local, and chunk boundaries cannot
    change any per-row reduction.

    ``offset`` must be a column NAME here (arrays cannot align with file
    chunks); a fit-time by-name offset travels with the model as usual.
    ``out_path`` streams results to a CSV (``fit`` or ``fit,se_fit``
    columns) instead of accumulating them — for scoring runs whose
    OUTPUT is also too big to hold; returns ``out_path``.

    ``.parquet``/``.pq`` paths stream row-group bands through the same
    flow (``_stream_io`` dispatch).

    ``trace=``/``metrics=`` observe the scoring run the way ``fit(...)``
    observes training: the tracer is installed as ambient for the loop, so
    the readers' per-chunk ``read`` events flow into it, and a ``score``
    event (rows, seconds, destination) is emitted per chunk."""
    from .obs import trace as _obs_trace
    tracer = _obs_trace.as_tracer(trace, metrics=metrics)
    if tracer is None:
        tracer = _obs_trace.resolve(None)  # inherit any ambient tracer
    off_kw = kwargs.get("offset")
    if off_kw is not None and not isinstance(off_kw, str):
        raise ValueError(
            "offset must be a column NAME when scoring from a file path "
            "(arrays cannot align with file chunks)")
    if out_path is not None and kwargs.get("type") == "terms":
        raise ValueError("out_path supports fit/se scoring, not type='terms'")
    _, num_chunks, read_chunk = _stream_io(path, chunk_bytes=chunk_bytes,
                                           native=native, levels=False)
    parts = []
    out_fh = open(out_path, "w") if out_path is not None else None
    wrote_header = False
    try:
        with _obs_trace.ambient(tracer):
            for i in range(num_chunks):
                cols = read_chunk(i)
                ncols = len(next(iter(cols.values()))) if cols else 0
                if ncols == 0:
                    continue
                kw = dict(kwargs)
                if isinstance(off_kw, str):
                    if off_kw not in cols:
                        raise KeyError(
                            f"offset column {off_kw!r} not found in file "
                            f"columns {list(cols)}")
                    kw["offset"] = np.asarray(cols[off_kw], np.float64)
                t0 = time.perf_counter()
                res = predict(model, cols, **kw)
                if tracer is not None:
                    tracer.emit("score", index=i, rows=ncols,
                                seconds=time.perf_counter() - t0,
                                out="file" if out_fh is not None else "memory")
                if out_fh is not None:
                    if isinstance(res, tuple):
                        if not wrote_header:
                            out_fh.write("fit,se_fit\n")
                            wrote_header = True
                        np.savetxt(out_fh, np.column_stack(res), fmt="%.17g",
                                   delimiter=",")
                    else:
                        if not wrote_header:
                            out_fh.write("fit\n")
                            wrote_header = True
                        np.savetxt(out_fh, np.asarray(res), fmt="%.17g")
                else:
                    parts.append(res)
    finally:
        if out_fh is not None:
            out_fh.close()
    if out_path is not None:
        if not wrote_header:
            raise ValueError(f"{path!r} contained no data rows")
        return out_path
    if not parts:
        raise ValueError(f"{path!r} contained no data rows")
    first = parts[0]
    if isinstance(first, tuple):  # se_fit: (fit, se)
        return tuple(np.concatenate([p[j] for p in parts])
                     for j in range(len(first)))
    if isinstance(first, TermsPrediction):
        return TermsPrediction(
            np.concatenate([p.matrix for p in parts], axis=0),
            first.columns, first.constant)
    return np.concatenate(parts)


def predict(model, data, **kwargs) -> np.ndarray:
    """Score new column-data through a formula-fitted model.

    Equivalent of ``predict.sparkLM`` (R/pkg/R/LM.R:87-100): rebuild the
    design matrix under the training ``Terms`` (which embeds the matchCols
    zero-filling, utils.scala:21-33) then X·beta.

    ``data`` may also be a CSV file PATH: scoring then streams the file
    in byte-range chunks through the identical per-chunk path
    (bit-parity with loading it whole); see :func:`_predict_from_path`
    for the path-only keywords (``chunk_bytes``, ``native``,
    ``out_path``).

    ``type="terms"`` returns a :class:`TermsPrediction` — per-term
    link-scale contributions centered at the training design means plus
    the constant, exactly R's ``predict(fit, type="terms")`` (offsets are
    excluded from the columns, as in R)."""
    if model.terms is None:
        raise ValueError(
            "model was fit from arrays, not a formula; call model.predict(X) "
            "with an aligned design matrix instead")
    if _is_path(data):
        return _predict_from_path(model, str(data), **kwargs)
    cols = as_columns(data)
    if kwargs.get("type") == "terms":
        extra = set(kwargs) - {"type"}
        if extra:
            raise ValueError(
                f"type='terms' takes no other predict arguments, got {extra}")
        # per-term centering walks column spans — a dense-only concern
        return _predict_terms(model, transform(cols, model.terms))
    # wide-factor terms score through the structured representation (no
    # one-hot materialization) — the same predicate fit's design="auto"
    # used, so scoring cost tracks fitting cost
    X = (transform_structured(cols, model.terms)
         if wants_structured(model.terms) else transform(cols, model.terms))
    # a fit-time by-name offset travels with the model (R's predict.glm uses
    # the stored model-frame offset); an explicit offset kwarg overrides
    if "offset" not in kwargs:
        off = _fit_time_offset(model, cols)
        if off is not None:
            kwargs["offset"] = off
    return model.predict(X, **kwargs)
