"""The online continuous-learning loop: chunks in, deployments out.

``OnlineLoop`` composes the pieces the previous PRs built into the
ROADMAP's "keeps thousands of per-tenant GLMs fresh under live traffic"
scenario:

  source chunks -> decayed suffstats (suffstats.py)
                -> drift gate (drift.py, obs/ primitives)
                -> gated refresh: closed-form gaussian re-solve, or a
                   warm-started fleet refit at the FIXED power-of-2
                   bucket (fleet/fitting.py ``start=``) — steady-state
                   refresh compiles NOTHING
                -> challenger gating through the existing shadow-scoring
                   A/B path (serve/engine.FamilyScorer)
                -> ``ModelFamily.deploy()`` through the generation
                   counter, so ``ReplicatedScorer.refresh()`` (and any
                   ``AsyncEngine`` over it) picks the new champion up
                   recompile-free
                -> a post-deploy regression watch that auto-rolls-back

Every decision is host float64 and deterministic: the same chunk stream
produces the same trace-event sequence (``chunk_ingested`` /
``drift_detected`` / ``refresh_start`` / ``refresh_end`` /
``auto_deploy`` / ``auto_rollback``), which the e2e test asserts.

Refresh semantics per family:

  * gaussian/identity — the decayed Gramian IS the fit:
    ``OnlineSuffStats.solve()`` returns the exact WLS coefficients of
    the decayed-weight dataset in closed form.  No refit, no compile.
  * everything else — IRLS reweights per iteration, so the loop retains
    a fixed-size per-tenant ring of recent rows (``window_rows``) and
    refreshes by a warm-started fleet refit over it: fixed (bucket,
    window_rows, p) shapes + ``start=`` from the deployed table mean one
    executable at the first refresh and zero afterwards.

Challenger gating: refreshed coefficients register as STAGED versions;
the existing FamilyScorer shadow path scores champion and challenger on
the retained window in one dispatch, and a challenger deploys only if
its held-out deviance does not regress beyond ``deviance_tolerance``.
Deployed tenants enter a ``watch_chunks``-chunk regression watch: on
each subsequent chunk the deployed model's deviance is compared against
the prior version's on the same rows, and a regression beyond
``rollback_tolerance`` triggers ``ModelFamily.rollback`` plus an
``auto_rollback`` event — the guardrail the e2e test exercises with a
seeded bad deploy.

Persistence: ``loop.save(path)`` (models/serialize.py v5) stores the
family (every version + deploy history), the suffstats, the row rings,
the drift-gate histograms and the watch state in one artifact;
``OnlineLoop.load(path)`` resumes bit-identically (test-enforced under
``prefetch=2``).

Crash durability: construct with ``journal=`` (a directory path or
:class:`~sparkglm_tpu.online.journal.OnlineJournal`) and every chunk's
raw input is journaled atomically BEFORE it is applied, with periodic
full-state snapshots; after a crash — including ``SIGKILL`` at any
point — :meth:`OnlineLoop.resume` loads the latest snapshot and
replays the surviving records through :meth:`step`, landing at the
exact chunk boundary with bit-identical statistics and the same
deploy/rollback decisions (journal.py module docstring argues why;
test-enforced with a real kill).
"""

from __future__ import annotations

import dataclasses
import time
import warnings

import numpy as np

from ..config import DEFAULT, NumericConfig
from ..data.groups import MIN_BUCKET, next_bucket
from ..data.pipeline import prefetch_iter
from ..models import hoststats
from ..obs import context as _obs_context
from ..obs import trace as _obs_trace
from .drift import DriftGate
from .suffstats import OnlineSuffStats

__all__ = ["OnlineLoop"]


class OnlineLoop:
    """Drive a :class:`~sparkglm_tpu.serve.ModelFamily` from live chunks
    (module docstring).

    Args:
      family: the served ``ModelFamily`` (every tenant deployed); its
        tenant order fixes the model axis everywhere here.
      rho: per-chunk decay of the sufficient statistics, in (0, 1].
      window_rows: per-tenant retained-row ring size (the warm-refit
        training window and the challenger-gate holdout).
      drift_threshold / reference_chunks / window_chunks / min_count:
        :class:`~sparkglm_tpu.online.drift.DriftGate` knobs.
      deviance_tolerance: max relative held-out deviance regression a
        challenger may show and still deploy.
      rollback_tolerance: max relative live regression vs the prior
        version before auto-rollback (defaults to deviance_tolerance).
      watch_chunks: post-deploy chunks the regression watch stays armed.
      jitter: ridge added to the closed-form solve's Gramian.
      tol / max_iter / batch: warm fleet-refit IRLS knobs.
      trace / metrics: obs/ wiring; events always aggregate into
        :meth:`report` even with no sink attached.
      telemetry: an :class:`~sparkglm_tpu.obs.export.Telemetry` — the
        runtime observability plane: the loop emits into its tracer (so
        cycle events land in the flight-recorder ring and the drift
        trigger dumps records) and its registry (so drift gauges export).
        Explicit ``trace=``/``metrics=`` win over the telemetry's.
      journal: a directory path or :class:`~sparkglm_tpu.online.journal.
        OnlineJournal` — arms the write-ahead journal (module docstring:
        crash durability).  An initial snapshot is written at attach
        time so :meth:`resume` always finds a base.
    """

    def __init__(self, family, *, rho: float = 0.99,
                 window_rows: int = 128,
                 drift_threshold: float = 0.25,
                 reference_chunks: int = 4, window_chunks: int = 4,
                 min_count: int = 8,
                 deviance_tolerance: float = 0.05,
                 rollback_tolerance: float | None = None,
                 watch_chunks: int = 4,
                 jitter: float = 0.0,
                 tol: float = 1e-8, max_iter: int = 50,
                 batch: str = "exact",
                 trace=None, metrics=None, telemetry=None,
                 journal=None, shard_label: str | None = None,
                 config: NumericConfig = DEFAULT):
        if window_rows < 1:
            raise ValueError(f"window_rows must be >= 1, got {window_rows}")
        if deviance_tolerance < 0:
            raise ValueError("deviance_tolerance must be >= 0")
        if watch_chunks < 1:
            raise ValueError(f"watch_chunks must be >= 1, got {watch_chunks}")
        self.family = family
        if family.family is None:
            raise ValueError(
                "the ModelFamily has no registered tenants yet; build it "
                "from a seed fleet first (ModelFamily.from_fleet)")
        tenants, B = family.deployed_matrix()
        self.labels = tenants
        self.K = len(tenants)
        self.p = B.shape[1]
        self._index = {t: k for k, t in enumerate(tenants)}
        self.glm_family = family.family
        self.link = family.link
        self.is_closed_form = (self.glm_family == "gaussian"
                               and self.link == "identity")
        self.rho = float(rho)
        self.window_rows = int(window_rows)
        self.deviance_tolerance = float(deviance_tolerance)
        self.rollback_tolerance = float(
            deviance_tolerance if rollback_tolerance is None
            else rollback_tolerance)
        self.watch_chunks = int(watch_chunks)
        self.jitter = float(jitter)
        self.tol = float(tol)
        self.max_iter = int(max_iter)
        self.batch = batch
        self.config = config
        # trace-id prefix for sharded deployments: shard "shard-01"
        # emits cycle ids "shard-01-cycle-000001" so per-shard streams
        # stay distinguishable after cross-process aggregation
        self.shard_label = shard_label
        self.telemetry = telemetry
        if telemetry is not None:
            if trace is None:
                trace = telemetry.tracer
            if metrics is None:
                metrics = telemetry.metrics
        tr = _obs_trace.as_tracer(trace, metrics=metrics)
        self.tracer = tr if tr is not None else _obs_trace.FitTracer()
        self.suffstats = OnlineSuffStats.init(tenants, self.p, rho=self.rho)
        self.gate = DriftGate(
            tenants, threshold=drift_threshold,
            reference_chunks=reference_chunks,
            window_chunks=window_chunks, min_count=min_count,
            tracer=self.tracer)
        self.bucket = next_bucket(self.K, MIN_BUCKET)
        W = self.window_rows
        # per-tenant row rings; w == 0 marks unfilled slots (weight-0
        # trash rows are inert in every fit/stat by the padding contract)
        self._Xw = np.zeros((self.K, W, self.p))
        self._yw = np.zeros((self.K, W))
        self._ww = np.zeros((self.K, W))
        self._ow = np.zeros((self.K, W))
        self._pos = np.zeros(self.K, np.int64)
        self._chunks = 0
        self._refreshes = 0
        # tenant -> {"prior": version, "left": chunks} regression watches
        self._watch: dict[str, dict] = {}
        self.journal = None
        if journal is not None:
            self.attach_journal(journal)

    # -- chunk ingestion -----------------------------------------------------

    def step(self, tenants, X, y, *, weights=None, offset=None) -> dict:
        """Absorb one chunk; returns a small summary dict
        (``drifted``/``deployed``/``rolled_back`` tenant tuples).

        One chunk is ONE TRACE: every event the cycle emits — ingest,
        watch/rollback, drift, refresh, shadow-gate ``scorer_kernel``,
        deploy — carries a deterministic ``cycle-NNNNNN`` trace id (the
        chunk counter), so a drift-triggered flight record reads as a
        correlated story, not interleaved noise.  The tracer is also
        installed ambient for the cycle so layers the loop calls into
        (FamilyScorer, the fleet kernels) emit into the same trace even
        when ``step`` is called directly rather than through :meth:`run`.
        """
        label = getattr(self, "shard_label", None)
        ctx = _obs_context.TraceContext(
            trace=f"{label + '-' if label else ''}"
                  f"cycle-{self._chunks + 1:06d}", span="cycle")
        with _obs_trace.ambient(self.tracer), _obs_context.use(ctx):
            chunk = self._chunks + 1
            if self.journal is not None:
                # write-ahead: the chunk's raw input is durable BEFORE
                # any state mutates, so a kill mid-apply replays it
                nbytes = self.journal.append(
                    chunk, tenants, X, y, weights, offset)
                self.tracer.emit("journal_append", chunk=chunk,
                                 rows=int(np.asarray(X).shape[0]),
                                 nbytes=int(nbytes))
            try:
                out = self._step(tenants, X, y, weights=weights,
                                 offset=offset)
            except BaseException:
                # _step rejected the chunk before any state mutated
                # (bad shapes, unknown tenant): withdraw its record so
                # resume() never replays input the live run refused.  If
                # the chunk counter DID advance the record stays —
                # replaying it from the last snapshot reconstructs the
                # fully-applied state a torn in-memory apply cannot.
                if self.journal is not None and self._chunks < chunk:
                    self.journal.withdraw(chunk)
                raise
            if (self.journal is not None
                    and self._chunks % self.journal.snapshot_every == 0):
                self._snapshot()
            return out

    def _step(self, tenants, X, y, *, weights=None, offset=None) -> dict:
        X = np.asarray(X, np.float64)
        y = np.asarray(y, np.float64)
        if X.ndim != 2 or X.shape[1] != self.p:
            raise ValueError(
                f"chunk design must be (n, {self.p}), got {X.shape}")
        n = X.shape[0]
        w = (np.ones(n) if weights is None
             else np.asarray(weights, np.float64))
        off = (np.zeros(n) if offset is None
               else np.asarray(offset, np.float64))
        tenants = np.asarray(tenants)
        try:
            tidx = np.array([self._index[str(t)] for t in tenants],
                            np.int64)
        except KeyError as exc:
            raise KeyError(
                f"unknown tenant {exc.args[0]!r}; the online loop serves "
                f"a fixed family of {self.K} tenants") from None
        self._chunks += 1
        present = sorted(set(int(k) for k in tidx))
        self.tracer.emit("chunk_ingested", chunk=self._chunks, rows=n,
                         tenants=len(present))

        # 1. regression watch on the PRE-refresh champions
        rolled = self._eval_watch(tidx, X, y, w, off)

        # 2. drift statistics under the (possibly just rolled-back)
        #    deployed table
        _, B = self.family.deployed_matrix()
        eta = np.einsum("np,np->n", X, B[tidx]) + off
        mu = hoststats.link_inverse(self.link, eta)
        per_tenant = {}
        for k in present:
            m = tidx == k
            dr = hoststats.dev_resids(self.glm_family, y[m], mu[m], w[m])
            per_tenant[self.labels[k]] = (
                np.abs(y[m] - mu[m]), float(np.sum(dr)), float(w[m].sum()))
        drifted = self.gate.observe_chunk(per_tenant)

        # 3. decayed sufficient statistics + retained-row rings
        self.suffstats.update(tenants, X, y, weights=w, offset=off)
        self._retain(tidx, X, y, w, off)

        deployed = self._refresh(drifted) if drifted else ()
        return dict(chunk=self._chunks, drifted=drifted,
                    deployed=deployed, rolled_back=rolled)

    def run(self, source, *, prefetch: int | None = None,
            ingest_workers: int | None = None,
            max_chunks: int | None = None, fault_plan=None) -> dict:
        """Drive :meth:`step` over a chunk source — a zero-arg callable
        returning an iterator of ``(tenants, X, y[, weights[, offset]])``
        tuples (or thunks realizing to one), the streaming-source
        convention; ``data/pipeline.tee_source`` splits one live stream
        between this loop and anything else.  ``prefetch`` pipelines
        chunk production (data/pipeline.py — bit-identical by the
        determinism contract there).  ``fault_plan`` (robust/faults.py)
        fires its ``kill_chunk_at`` schedule at each chunk boundary —
        the chaos test's process kill, exercised against the journal.
        ``ingest_workers=N`` fans chunk production across N OS worker
        processes when the source supports it (``data/ingest.py``
        ``ShardedSource``; deterministic chunk order, so every decision
        the loop makes is unchanged).  Returns :meth:`report`.
        """
        if ingest_workers is not None:
            if not hasattr(source, "with_workers"):
                raise ValueError(
                    "ingest_workers= needs an index-addressable source "
                    "(data/ingest.ShardedSource); got a plain callable")
            source = source.with_workers(int(ingest_workers))
        it = (source() if prefetch is None else
              prefetch_iter(source, prefetch, auto_degrade=False))
        with _obs_trace.ambient(self.tracer):
            for i, item in enumerate(it):
                if max_chunks is not None and i >= max_chunks:
                    break
                if callable(item):
                    item = item()
                if fault_plan is not None:
                    # absolute chunk ordinal about to be applied, so a
                    # schedule means the same boundary across resumes
                    fault_plan.on_online_chunk(self._chunks + 1)
                self.step(*item[:3],
                          weights=item[3] if len(item) > 3 else None,
                          offset=item[4] if len(item) > 4 else None)
        return self.report()

    def _retain(self, tidx, X, y, w, off) -> None:
        """Append chunk rows to each tenant's fixed-size ring (oldest
        rows overwrite first; w == 0 marks never-filled slots)."""
        W = self.window_rows
        for k in sorted(set(int(t) for t in tidx)):
            m = tidx == k
            idx = (self._pos[k] + np.arange(int(m.sum()))) % W
            self._Xw[k, idx] = X[m]
            self._yw[k, idx] = y[m]
            self._ww[k, idx] = w[m]
            self._ow[k, idx] = off[m]
            self._pos[k] = (self._pos[k] + int(m.sum())) % W

    # -- refresh -------------------------------------------------------------

    def _refresh(self, drifted) -> tuple:
        """Recompute drifted members, gate them through shadow scoring,
        deploy the survivors; returns the deployed tenants."""
        mode = "closed_form" if self.is_closed_form else "warm_refit"
        self.tracer.emit("refresh_start", mode=mode,
                         tenants=len(drifted), chunk=self._chunks)
        t0 = time.perf_counter()
        from ..fleet.kernel import fleet_kernel_cache_size
        n_exec0 = fleet_kernel_cache_size()
        if self.is_closed_form:
            beta = self.suffstats.solve(jitter=self.jitter)
        else:
            beta = self._warm_refit()
        executables = fleet_kernel_cache_size() - n_exec0
        self._refreshes += 1
        self.tracer.emit("refresh_end", mode=mode, tenants=len(drifted),
                         executables=int(executables), chunk=self._chunks,
                         seconds=time.perf_counter() - t0)

        # stage challengers for the drifted tenants (never auto-deploy:
        # the shadow gate decides)
        challengers: dict[str, int] = {}
        for t in drifted:
            b = beta[self._index[t]]
            if not np.all(np.isfinite(b)):
                continue  # no mass yet / singular — nothing to deploy
            mdl = dataclasses.replace(self.family.model(t),
                                      coefficients=np.asarray(b))
            challengers[t] = self.family.register(t, mdl, deploy=False)
        if not challengers:
            return ()
        accepted = self._gate_challengers(challengers)
        deployed = []
        for t in sorted(accepted, key=lambda t: self._index[t]):
            prior = self.family.deployed_version(t)
            self.family.deploy(t, challengers[t])
            self._watch[t] = dict(prior=int(prior),
                                  left=self.watch_chunks)
            self.tracer.emit("auto_deploy", tenant=t,
                             version=int(challengers[t]),
                             prior=int(prior), chunk=self._chunks)
            deployed.append(t)
        if deployed:
            # drift is now measured against the new champions
            self.gate.rearm()
        return tuple(deployed)

    def _warm_refit(self) -> np.ndarray:
        """One warm-started fleet refit over the retained rings at the
        FIXED (bucket, window_rows, p) shapes — the steady-state
        zero-compile path (``start=`` threads into the warm fleet
        kernel; trash tenants/rows stay inert)."""
        from ..fleet.fitting import glm_fit_fleet
        _, B = self.family.deployed_matrix()
        has_off = bool(np.any(self._ow[self._ww > 0])) if np.any(
            self._ww > 0) else False
        with warnings.catch_warnings():
            # tenants with an unfilled ring are singular/non-converged by
            # construction; their NaN rows are filtered above
            warnings.simplefilter("ignore")
            fleet = glm_fit_fleet(
                self._Xw, self._yw, weights=self._ww,
                offset=self._ow if has_off else None,
                family=self.glm_family, link=self.link,
                labels=self.labels, bucket=self.bucket, start=B,
                tol=self.tol, max_iter=self.max_iter, batch=self.batch,
                config=self.config)
        return np.asarray(fleet.coefficients, np.float64)

    def _gate_challengers(self, challengers: dict) -> list:
        """Shadow-score champion vs challenger on the retained window
        through the existing FamilyScorer A/B path; accept challengers
        whose held-out deviance does not regress beyond tolerance."""
        rows_t, rows_X, rows_y, rows_w, rows_o = [], [], [], [], []
        for t in sorted(challengers, key=lambda t: self._index[t]):
            k = self._index[t]
            m = self._ww[k] > 0
            if not np.any(m):
                continue
            rows_t.extend([t] * int(m.sum()))
            rows_X.append(self._Xw[k, m])
            rows_y.append(self._yw[k, m])
            rows_w.append(self._ww[k, m])
            rows_o.append(self._ow[k, m])
        if not rows_t:
            return []
        X = np.concatenate(rows_X)
        y = np.concatenate(rows_y)
        w = np.concatenate(rows_w)
        off = np.concatenate(rows_o)
        sc = self.family.scorer(shadow=dict(challengers))
        mu_champ, mu_chal = sc.score(
            rows_t, X, offset=off if np.any(off) else None)
        accepted = []
        tl = np.asarray(rows_t, object)
        tol = self.deviance_tolerance
        for t in sorted(challengers, key=lambda t: self._index[t]):
            m = tl == t
            if not np.any(m):
                continue
            dev_champ = float(np.sum(hoststats.dev_resids(
                self.glm_family, y[m], mu_champ[m], w[m])))
            dev_chal = float(np.sum(hoststats.dev_resids(
                self.glm_family, y[m], mu_chal[m], w[m])))
            if np.isfinite(dev_chal) and (
                    dev_chal <= dev_champ * (1.0 + tol) + 1e-12):
                accepted.append(t)
        return accepted

    # -- regression watch / rollback ----------------------------------------

    def _eval_watch(self, tidx, X, y, w, off) -> tuple:
        """Compare each watched tenant's deployed model against its
        prior version on this chunk's rows; roll back on regression."""
        if not self._watch:
            return ()
        rolled = []
        for t in sorted(self._watch, key=lambda t: self._index[t]):
            k = self._index[t]
            m = tidx == k
            if not np.any(m):
                continue
            st = self._watch[t]
            cur_v = self.family.deployed_version(t)
            b_cur = np.asarray(self.family.model(t).coefficients)
            b_prior = np.asarray(
                self.family.model(t, st["prior"]).coefficients)
            dev_cur = self._chunk_dev(b_cur, X[m], y[m], w[m], off[m])
            dev_prior = self._chunk_dev(b_prior, X[m], y[m], w[m], off[m])
            if (not np.isfinite(dev_cur)
                    or dev_cur > dev_prior
                    * (1.0 + self.rollback_tolerance) + 1e-12):
                restored = self.family.rollback(t)
                self.tracer.emit("auto_rollback", tenant=t,
                                 from_version=int(cur_v),
                                 to_version=int(restored),
                                 chunk=self._chunks)
                del self._watch[t]
                rolled.append(t)
                continue
            st["left"] -= 1
            if st["left"] <= 0:
                del self._watch[t]
        return tuple(rolled)

    def _chunk_dev(self, beta, X, y, w, off) -> float:
        eta = X @ beta + off
        mu = hoststats.link_inverse(self.link, eta)
        return float(np.sum(hoststats.dev_resids(self.glm_family, y, mu,
                                                 w)))

    # -- tenant growth (serve/growth.py) -------------------------------------

    def grow(self, models: dict) -> dict:
        """Grow the tenant set without rebuilding the loop: register and
        deploy each ``{tenant: model}`` in the family (their version 1 —
        growth deploys, there is no prior champion to stage against) and
        migrate EVERY piece of loop state to the new sorted tenant
        order in one step:

          * suffstats — :meth:`OnlineSuffStats.grow`: surviving rows are
            byte-copied, new tenants start at zero mass;
          * drift gate — :meth:`DriftGate.grow`: histograms carry over,
            window clocks untouched;
          * retained-row rings and ring positions — permuted to the new
            order (copied, never recomputed);
          * ``bucket`` — re-derived from the grown K, so the next warm
            refit runs at the grown fleet bucket (serving-side warm of
            the matching table shapes is the caller's job:
            ``ReplicatedScorer.prewarm_tenant_axis`` BEFORE calling
            this — serve/growth.py sequences the two).

        Family registration and loop migration are one atomic step from
        the loop's point of view: ``step()`` must never see the family's
        sorted tenant order disagree with its own index (rows would
        score against the wrong coefficients).  With a journal attached
        the grown state snapshots immediately — growth mutates state
        outside the per-chunk WAL stream, so it must be durable before
        the next record lands (a kill between registration and snapshot
        resumes to the clean pre-growth state).  Returns
        ``{added, tenants, bucket}``.
        """
        new = {str(t): m for t, m in models.items()}
        dup = sorted(set(new) & set(self.labels))
        if dup:
            raise ValueError(
                f"tenants already in the family: {dup[:4]}"
                f"{'...' if len(dup) > 4 else ''}")
        if not new:
            return dict(added=(), tenants=self.K, bucket=self.bucket)
        for t in sorted(new):
            self.family.register(t, new[t])  # v1 auto-deploys
        tenants, _B = self.family.deployed_matrix()
        old_index = self._index
        self.labels = tenants
        self.K = len(tenants)
        self._index = {t: k for k, t in enumerate(tenants)}
        old_bucket, self.bucket = self.bucket, next_bucket(self.K,
                                                           MIN_BUCKET)
        self.suffstats = self.suffstats.grow(tenants)
        self.gate.grow(tenants)
        W = self.window_rows
        Xw = np.zeros((self.K, W, self.p))
        yw = np.zeros((self.K, W))
        ww = np.zeros((self.K, W))
        ow = np.zeros((self.K, W))
        pos = np.zeros(self.K, np.int64)
        for t, j in old_index.items():
            k = self._index[t]
            Xw[k] = self._Xw[j]
            yw[k] = self._yw[j]
            ww[k] = self._ww[j]
            ow[k] = self._ow[j]
            pos[k] = self._pos[j]
        self._Xw, self._yw, self._ww, self._ow, self._pos = (
            Xw, yw, ww, ow, pos)
        self.tracer.emit("family_grow", added=len(new), tenants=self.K,
                         bucket_before=int(old_bucket),
                         bucket_after=int(self.bucket),
                         chunk=self._chunks)
        if self.journal is not None:
            self._snapshot()
        return dict(added=tuple(sorted(new)), tenants=self.K,
                    bucket=self.bucket)

    # -- manual deploy hook --------------------------------------------------

    def deploy(self, tenant: str, model, *, watch: bool = True) -> int:
        """Register + deploy ``model`` for ``tenant`` outside the gate
        (operator override / canary seeding).  ``watch=True`` arms the
        same regression watch the gated path uses, so a bad manual
        deploy auto-rolls-back — the e2e seeded-regression scenario."""
        tenant = str(tenant)
        prior = self.family.deployed_version(tenant)
        version = self.family.register(tenant, model, deploy=True)
        if watch and prior is not None:
            self._watch[tenant] = dict(prior=int(prior),
                                       left=self.watch_chunks)
        return version

    # -- reporting -----------------------------------------------------------

    def report(self) -> dict:
        """The tracer's aggregate report (its ``online`` block carries
        the chunk/drift/refresh/deploy census)."""
        return self.tracer.report()

    # -- crash durability (online/journal.py) --------------------------------

    def attach_journal(self, journal, *, snapshot: bool = True) -> None:
        """Arm the write-ahead journal.  ``snapshot=True`` (default)
        snapshots the CURRENT state immediately, so resume always finds
        a base even if the process dies before the first cadence
        snapshot."""
        from .journal import OnlineJournal
        if not isinstance(journal, OnlineJournal):
            journal = OnlineJournal(journal)
        self.journal = journal
        if snapshot:
            self._snapshot()

    def _snapshot(self) -> None:
        nbytes = self.journal.snapshot(self)
        self.tracer.emit("journal_snapshot", chunk=self._chunks,
                         nbytes=int(nbytes),
                         suffstats_digest=self.suffstats.digest())

    @classmethod
    def resume(cls, journal, *, trace=None, metrics=None) -> "OnlineLoop":
        """Rebuild a loop from its journal after a crash: load the
        latest snapshot, replay every record past it through
        :meth:`step` in chunk order, re-arm the journal.  The result is
        bit-identical to the uninterrupted run at the same chunk
        boundary (module docstring of journal.py; test-enforced under
        ``SIGKILL``)."""
        from .journal import OnlineJournal
        if not isinstance(journal, OnlineJournal):
            journal = OnlineJournal(journal)
        snap = journal.latest_snapshot()
        if snap is None:
            raise FileNotFoundError(
                f"no snapshot in journal directory {journal.directory!r}; "
                "was the journal ever attached to a loop?")
        chunk0, path = snap
        loop = cls.load(path, trace=trace, metrics=metrics)
        records = journal.records(after=loop._chunks)
        for _idx, rpath in records:
            tenants, X, y, w, off = journal.load_record(rpath)
            loop.step(tenants, X, y, weights=w, offset=off)
        loop.tracer.emit("journal_replay", snapshot_chunk=int(chunk0),
                         replayed=len(records), chunk=loop._chunks,
                         suffstats_digest=loop.suffstats.digest())
        # re-arm; the attach snapshot absorbs the replayed records so
        # the next crash replays only post-resume chunks
        loop.attach_journal(journal)
        return loop

    # -- persistence (models/serialize.py v5) --------------------------------

    def save(self, path) -> None:
        from ..models.serialize import save_model
        save_model(self, path)

    @classmethod
    def load(cls, path, *, trace=None, metrics=None) -> "OnlineLoop":
        from ..models.serialize import load_model
        loop = load_model(path)
        if not isinstance(loop, cls):
            raise ValueError(
                f"{path!r} is not an OnlineLoop artifact "
                f"(got {type(loop).__name__})")
        if trace is not None or metrics is not None:
            tr = _obs_trace.as_tracer(trace, metrics=metrics)
            loop.tracer = tr if tr is not None else loop.tracer
            loop.gate.tracer = loop.tracer
        return loop

    def _export(self) -> tuple[dict, dict]:
        """Arrays + JSON-able meta for serialize.py (the family itself is
        exported alongside by ``_save_online``)."""
        ss_arrays, ss_meta = self.suffstats._export()
        arrays = {f"ss__{k}": v for k, v in ss_arrays.items()}
        arrays.update(win__X=self._Xw, win__y=self._yw, win__w=self._ww,
                      win__off=self._ow, win__pos=self._pos)
        meta = dict(
            rho=self.rho, window_rows=self.window_rows,
            drift_threshold=self.gate.threshold,
            reference_chunks=self.gate.reference_chunks,
            window_chunks=self.gate.window_chunks,
            min_count=self.gate.min_count,
            deviance_tolerance=self.deviance_tolerance,
            rollback_tolerance=self.rollback_tolerance,
            watch_chunks=self.watch_chunks, jitter=self.jitter,
            tol=self.tol, max_iter=self.max_iter, batch=self.batch,
            chunks=self._chunks, refreshes=self._refreshes,
            suffstats=ss_meta, gate=self.gate._export(),
            watch={t: dict(v) for t, v in sorted(self._watch.items())})
        return arrays, meta

    @classmethod
    def _restore(cls, family, arrays: dict, meta: dict) -> "OnlineLoop":
        loop = cls(
            family, rho=meta["rho"], window_rows=meta["window_rows"],
            drift_threshold=meta["drift_threshold"],
            reference_chunks=meta["reference_chunks"],
            window_chunks=meta["window_chunks"],
            min_count=meta["min_count"],
            deviance_tolerance=meta["deviance_tolerance"],
            rollback_tolerance=meta["rollback_tolerance"],
            watch_chunks=meta["watch_chunks"], jitter=meta["jitter"],
            tol=meta["tol"], max_iter=meta["max_iter"],
            batch=meta["batch"])
        ss_arrays = {k[4:]: v for k, v in arrays.items()
                     if k.startswith("ss__")}
        loop.suffstats = OnlineSuffStats._restore(ss_arrays,
                                                  meta["suffstats"])
        loop._Xw = np.asarray(arrays["win__X"], np.float64)
        loop._yw = np.asarray(arrays["win__y"], np.float64)
        loop._ww = np.asarray(arrays["win__w"], np.float64)
        loop._ow = np.asarray(arrays["win__off"], np.float64)
        loop._pos = np.asarray(arrays["win__pos"], np.int64)
        loop._chunks = int(meta["chunks"])
        loop._refreshes = int(meta["refreshes"])
        loop.gate = DriftGate._restore(loop.labels, meta["gate"],
                                       tracer=loop.tracer)
        loop._watch = {t: dict(prior=int(v["prior"]), left=int(v["left"]))
                       for t, v in meta["watch"].items()}
        return loop
