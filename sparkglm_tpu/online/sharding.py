"""Sharded continuous learning: one :class:`OnlineLoop` writer per
tenant shard, combined information-weighted.

One loop over a large fleet serializes every chunk through one writer
and one journal — a single slow disk or one crash stalls learning for
every tenant.  :class:`ShardedOnlineLoop` partitions the tenant axis
into ``n_shards`` disjoint shards, each a full :class:`OnlineLoop` over
its own sub-:class:`ModelFamily` with its OWN write-ahead journal
(``shard-00/``, ``shard-01/``, ... under one root).  Rows route to
shards by a stable hash of the tenant label, so the assignment survives
growth and resumes; every shard steps on every chunk (possibly with
zero rows), which keeps the one-global-decay-clock semantics of the
unsharded loop — the combined statistics are BIT-IDENTICAL to an
unsharded loop fed the same chunks (test-enforced).

Combination follows elastic/combine.py's information weighting
(PAPERS.md arXiv:2111.00032): each shard's per-tenant Gramian IS its
information matrix, so

  ``beta_comb = (sum_s G_s)^{-1} sum_s G_s beta_s``

via :func:`~sparkglm_tpu.elastic.combine.combine_glm` — for the
disjoint partition each tenant has one contributing shard and the
combine degenerates to that shard's solve, but the formula (and
:meth:`combined_suffstats`'s additive merge) stays exact under
replicated assignments too.

Crash durability is per shard: SIGKILL takes the process, but each
shard's journal replays independently — :meth:`resume` rebuilds every
shard loop bit-for-bit (journal.py's contract) and the combined digest
equals the uninterrupted run's.  Deploys and rollbacks a shard's gate
decides sync back into the MASTER family immediately, so the serving
plane (one family, N engines — serve/pool.py) never sees shard
boundaries.
"""

from __future__ import annotations

import os
import zlib

import numpy as np

from .loop import OnlineLoop
from .suffstats import OnlineSuffStats

__all__ = ["ShardedOnlineLoop", "shard_of"]


def shard_of(tenant: str, n_shards: int) -> int:
    """Stable tenant -> shard assignment: crc32 of the label, mod the
    shard count.  Pure function of the label (no registration order, no
    RNG), so growth and resume land every tenant on the same shard."""
    return zlib.crc32(str(tenant).encode()) % int(n_shards)


class ShardedOnlineLoop:
    """Partition an online-learning plane over tenant shards (module
    doc).

    Args:
      family: the MASTER served :class:`ModelFamily` (every tenant
        deployed).  Shard sub-families are built from its deployed
        members; gate decisions sync back into it.
      n_shards: number of shard writers (>= 1).
      journal: optional journal ROOT — a directory under which each
        shard arms its own ``OnlineJournal`` at ``shard-NN/``.
      trace / metrics / telemetry: obs/ wiring, shared by every shard
        loop (events carry the shard in their ``chunk`` trace ids).
      **loop_kwargs: forwarded to every shard's :class:`OnlineLoop`
        (rho, window_rows, drift/gate knobs, ...).
    """

    def __init__(self, family, n_shards: int, *, journal=None,
                 trace=None, metrics=None, telemetry=None,
                 **loop_kwargs):
        if int(n_shards) < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        self.family = family
        self.n_shards = int(n_shards)
        tenants = family.tenants()
        if not tenants:
            raise ValueError(
                "the ModelFamily has no registered tenants yet; build it "
                "from a seed fleet first (ModelFamily.from_fleet)")
        empty = [s for s in range(self.n_shards)
                 if not any(shard_of(t, self.n_shards) == s
                            for t in tenants)]
        if empty:
            raise ValueError(
                f"shards {empty} would start with no tenants "
                f"({len(tenants)} tenants over {n_shards} shards); use "
                f"fewer shards or more tenants")
        self.loops: list[OnlineLoop] = []
        for s in range(self.n_shards):
            sub = self._sub_family(s, [t for t in tenants
                                       if shard_of(t, self.n_shards) == s])
            self.loops.append(OnlineLoop(
                sub, trace=trace, metrics=metrics, telemetry=telemetry,
                shard_label=f"shard-{s:02d}", **loop_kwargs))
        self._chunks = 0
        if journal is not None:
            self.attach_journal(journal)

    def _sub_family(self, s: int, tenants):
        from ..serve.registry import ModelFamily
        sub = ModelFamily(f"{self.family.name}-shard{s:02d}")
        for t in tenants:
            sub.register(t, self.family.model(t))  # deployed member, v1
        return sub

    # -- routing -------------------------------------------------------------

    def shard_of(self, tenant: str) -> int:
        return shard_of(tenant, self.n_shards)

    @property
    def labels(self) -> tuple:
        return self.family.tenants()

    # -- chunk ingestion ------------------------------------------------------

    def step(self, tenants, X, y, *, weights=None, offset=None) -> dict:
        """Route one chunk's rows to their shards and step EVERY shard
        (zero-row slices included: the decay/window clocks of all shards
        advance together, preserving the unsharded loop's one-global-
        clock semantics).  Shard deploys/rollbacks sync into the master
        family before returning.  Returns the merged summary dict."""
        X = np.asarray(X, np.float64)
        y = np.asarray(y, np.float64)
        n = X.shape[0] if X.ndim == 2 else 0
        w = None if weights is None else np.asarray(weights, np.float64)
        off = None if offset is None else np.asarray(offset, np.float64)
        labels = np.asarray(tenants)
        sidx = np.array([shard_of(t, self.n_shards) for t in labels],
                        np.int64) if n else np.zeros(0, np.int64)
        self._chunks += 1
        drifted, deployed, rolled = [], [], []
        for s, loop in enumerate(self.loops):
            m = sidx == s
            out = loop.step(
                labels[m], X[m], y[m],
                weights=None if w is None else w[m],
                offset=None if off is None else off[m])
            drifted.extend(out["drifted"])
            deployed.extend(out["deployed"])
            rolled.extend(out["rolled_back"])
            self._sync_master(loop, out)
        return dict(chunk=self._chunks, drifted=tuple(sorted(drifted)),
                    deployed=tuple(sorted(deployed)),
                    rolled_back=tuple(sorted(rolled)))

    def _sync_master(self, loop: OnlineLoop, out: dict) -> None:
        """Publish a shard's gate decisions to the master family: a
        deployed refresh registers + deploys the shard's new champion
        (one generation bump -> every serving scorer re-snapshots,
        recompile-free); a rollback rolls the master back too."""
        for t in out["deployed"]:
            self.family.register(t, loop.family.model(t), deploy=True)
        for t in out["rolled_back"]:
            self.family.rollback(t)

    def run(self, source, *, max_chunks: int | None = None,
            fault_plan=None) -> dict:
        """Drive :meth:`step` over a chunk source (the streaming-source
        convention of :meth:`OnlineLoop.run`).  ``fault_plan`` fires its
        ``kill_chunk_at`` schedule at each chunk boundary — the chaos
        test SIGKILLs the whole process mid-stream and resumes every
        shard from its own journal."""
        it = source()
        for i, item in enumerate(it):
            if max_chunks is not None and i >= max_chunks:
                break
            if callable(item):
                item = item()
            if fault_plan is not None:
                fault_plan.on_online_chunk(self._chunks + 1)
            self.step(*item[:3],
                      weights=item[3] if len(item) > 3 else None,
                      offset=item[4] if len(item) > 4 else None)
        return dict(chunks=self._chunks,
                    shards=[lp.report().get("online", {})
                            for lp in self.loops])

    # -- growth (serve/growth.py) ---------------------------------------------

    def grow(self, models: dict) -> dict:
        """Grow the tenant set: each new tenant routes to its hash shard
        (an existing shard — the stable assignment never reshuffles old
        tenants) and migrates that shard's loop state via
        :meth:`OnlineLoop.grow`; the master family registers the same
        members so serving and learning stay one tenant set."""
        new = {str(t): m for t, m in models.items()}
        dup = sorted(set(new) & set(self.family.tenants()))
        if dup:
            raise ValueError(
                f"tenants already in the family: {dup[:4]}"
                f"{'...' if len(dup) > 4 else ''}")
        per_shard: dict[int, dict] = {}
        for t in sorted(new):
            per_shard.setdefault(shard_of(t, self.n_shards), {})[t] = new[t]
        for s, sub in sorted(per_shard.items()):
            self.loops[s].grow(sub)
        for t in sorted(new):
            self.family.register(t, new[t])  # v1 auto-deploys
        return dict(added=tuple(sorted(new)),
                    tenants=len(self.family.tenants()),
                    shards={s: tuple(sorted(sub))
                            for s, sub in sorted(per_shard.items())})

    # -- combination (elastic/combine.py semantics) ---------------------------

    def combined_suffstats(self) -> OnlineSuffStats:
        """Merge every shard's decayed statistics into one accumulator
        over the union tenant set (sorted — the master family's order).
        Rows are SUMMED per label across shards: for the disjoint
        partition that is a byte-copy from the owning shard; under
        replicated assignments it is the exact additive combine (the
        Gramians are the informations).  The global chunk clock is the
        shared step count."""
        labels = tuple(sorted({t for lp in self.loops
                               for t in lp.suffstats.labels}))
        p = self.loops[0].p
        rho = self.loops[0].rho
        out = OnlineSuffStats.init(labels, p, rho=rho)
        idx = {t: k for k, t in enumerate(labels)}
        for lp in self.loops:
            ss = lp.suffstats
            for j, t in enumerate(ss.labels):
                k = idx[t]
                out.G[k] += ss.G[j]
                out.r[k] += ss.r[j]
                out.wsum[k] += ss.wsum[j]
        out.chunks = max(lp.suffstats.chunks for lp in self.loops)
        return out

    def combined_solve(self, *, jitter: float = 0.0) -> tuple:
        """Information-weighted combined coefficients
        ``(labels, (K, p) beta)`` via
        :func:`~sparkglm_tpu.elastic.combine.combine_glm` per tenant:
        ``(sum_s G_s)^{-1} sum_s G_s beta_s`` over the shards holding
        that tenant.  Massless tenants come back NaN (the loop's
        skip-deploy convention)."""
        from ..elastic.combine import combine_glm
        labels = tuple(sorted({t for lp in self.loops
                               for t in lp.suffstats.labels}))
        p = self.loops[0].p
        beta = np.full((len(labels), p), np.nan)
        shard_beta = [lp.suffstats.solve(jitter=jitter)
                      for lp in self.loops]
        for k, t in enumerate(labels):
            infos, betas = [], []
            for s, lp in enumerate(self.loops):
                ss = lp.suffstats
                if t not in ss.labels:
                    continue
                j = ss.labels.index(t)
                if ss.wsum[j] <= 0.0 or not np.all(
                        np.isfinite(shard_beta[s][j])):
                    continue
                infos.append(ss.G[j])
                betas.append(shard_beta[s][j])
            if infos:
                beta[k] = combine_glm(infos, betas, jitter=jitter)
        return labels, beta

    def digest(self) -> str:
        """sha256 of the COMBINED accumulator — what the chaos test
        compares across kill/resume against an uninterrupted control."""
        return self.combined_suffstats().digest()

    def shard_digests(self) -> tuple:
        return tuple(lp.suffstats.digest() for lp in self.loops)

    # -- crash durability -----------------------------------------------------

    def attach_journal(self, root, *, snapshot: bool = True) -> None:
        """Arm one write-ahead journal PER SHARD under ``root``
        (``shard-00/``, ``shard-01/``, ...) — independent writers, so
        one shard's fsync stall or torn chunk never blocks or corrupts
        another's stream."""
        self.journal_root = os.fspath(root)
        for s, loop in enumerate(self.loops):
            loop.attach_journal(self._shard_dir(self.journal_root, s),
                                snapshot=snapshot)

    @staticmethod
    def _shard_dir(root: str, s: int) -> str:
        return os.path.join(os.fspath(root), f"shard-{s:02d}")

    @classmethod
    def resume(cls, root, *, trace=None, metrics=None,
               family=None) -> "ShardedOnlineLoop":
        """Rebuild after a crash: every ``shard-NN/`` journal under
        ``root`` replays independently through :meth:`OnlineLoop.resume`
        (each bit-identical to its uninterrupted shard), then the master
        family is reassembled from the shard families' deployed members
        (or updated in place when the serving-plane ``family`` is
        passed).  The combined digest equals the uninterrupted run's at
        the same chunk boundary."""
        root = os.fspath(root)
        dirs = sorted(d for d in os.listdir(root)
                      if d.startswith("shard-")
                      and os.path.isdir(os.path.join(root, d)))
        if not dirs:
            raise FileNotFoundError(
                f"no shard-NN journal directories under {root!r}")
        loops = []
        for d in dirs:
            lp = OnlineLoop.resume(os.path.join(root, d), trace=trace,
                                   metrics=metrics)
            lp.shard_label = d  # "shard-NN": labelled cycle traces resume
            loops.append(lp)
        obj = cls.__new__(cls)
        obj.n_shards = len(loops)
        obj.loops = loops
        obj._chunks = max(lp._chunks for lp in loops)
        obj.journal_root = root
        if family is None:
            from ..serve.registry import ModelFamily
            base = loops[0].family
            family = ModelFamily(base.name.rsplit("-shard", 1)[0])
            for lp in loops:
                for t in lp.family.tenants():
                    family.register(t, lp.family.model(t))
        else:
            for lp in loops:
                for t in lp.family.tenants():
                    dv = lp.family.deployed_version(t)
                    if t not in family.tenants():
                        family.register(t, lp.family.model(t, dv))
                    else:
                        family.register(t, lp.family.model(t, dv),
                                        deploy=True)
        obj.family = family
        return obj
