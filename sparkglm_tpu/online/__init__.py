"""Online continuous learning: decayed sufficient statistics, drift
gates, and the refresh/deploy/rollback loop (ROADMAP item 3; the
split-then-combine treatment of PAPERS.md arXiv:2111.00032 with
reweighting-based warm refits per arXiv:2406.02769).

  suffstats.py  ``OnlineSuffStats`` — exponentially-decayed Gramian /
                score accumulators; closed-form gaussian re-solve.
  drift.py      ``DriftGate`` — frozen-reference vs rolling-window
                log2-histogram drift detection over obs/ primitives.
  loop.py       ``OnlineLoop`` — chunks -> suffstats -> gated refresh ->
                ``ModelFamily.deploy()`` -> regression-gated rollback.
  journal.py    ``OnlineJournal`` — write-ahead chunk journal + periodic
                snapshots on robust/checkpoint.py's atomic write-rename;
                ``OnlineLoop.resume`` replays to the exact chunk
                boundary bit-identically after a kill.
  sharding.py   ``ShardedOnlineLoop`` — one loop writer per tenant
                shard, each with its own journal; shard statistics
                combine information-weighted (elastic/combine.py) into
                state bit-identical to the unsharded loop.

Front-end: ``sparkglm_tpu.online_fleet(...)`` (api.py) seeds a fleet fit
and returns a ready loop.
"""

from .drift import DriftGate
from .journal import OnlineJournal
from .loop import OnlineLoop
from .sharding import ShardedOnlineLoop, shard_of
from .suffstats import OnlineSuffStats

__all__ = ["DriftGate", "OnlineJournal", "OnlineLoop", "OnlineSuffStats",
           "ShardedOnlineLoop", "shard_of"]
