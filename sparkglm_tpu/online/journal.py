"""Crash-durable write-ahead journal for the online loop.

``OnlineLoop`` keeps all of its state in process memory — kill the
process mid-stream and the decayed suffstats, drift histograms, row
rings and deploy history are gone; ``loop.save()`` is a manual
checkpoint the operator has to remember to call.  :class:`OnlineJournal`
makes durability automatic with the classic WAL discipline, built on
the atomic write-rename machinery in ``robust/checkpoint.py``:

  * ``append(chunk, ...)`` — BEFORE a chunk is applied, its raw INPUT
    (tenants / X / y / weights / offset) is journaled as
    ``chunk-NNNNNN.npz`` via :func:`~sparkglm_tpu.robust.checkpoint.
    atomic_savez` (temp file + fsync + ``os.replace``: a record either
    exists whole or not at all, never torn).
  * ``snapshot(loop)`` — every ``snapshot_every`` chunks (and once at
    attach time, so resume ALWAYS finds a base) the loop's full state is
    serialized through ``models/serialize.py`` v5 into
    ``snapshot-NNNNNN.npz``, again atomically; records at or before the
    snapshot chunk are then pruned.
  * resume (``OnlineLoop.resume(journal_dir)``) — load the latest
    snapshot, then REPLAY every surviving record through ``step()`` in
    chunk order.

Why replay is bit-identical: every decision ``step()`` makes is
deterministic host float64 over (current state, chunk input) — the
suffstats einsums accumulate in fixed bracketing, the drift gate and
shadow gate are pure functions of state, and serialize v5 round-trips
state byte-for-byte (test-pinned).  Journaling the chunk INPUT (rather
than a state delta) therefore reproduces the exact accumulation order
the healthy run performed — after replay the suffstats, drift gate,
row rings, regression watches AND the deploy/rollback decisions match
the uninterrupted run bit-for-bit (PARITY, test-enforced with a real
``SIGKILL``).

The WAL ordering ("journal, then apply") means a kill at ANY point —
mid-append, between append and apply, mid-apply, mid-snapshot — loses
nothing: a torn append never becomes a file, an applied-but-unsnapshot
chunk is replayed from its record, a torn snapshot leaves the previous
snapshot + records covering the gap.  The converse invariant also holds:
a chunk ``step()`` REJECTS before mutating state (bad shapes, unknown
tenant) has its record withdrawn (:meth:`OnlineJournal.withdraw`), so
resume never replays input the live run refused.
"""

from __future__ import annotations

import io
import os
import re
import threading
from typing import Optional

import numpy as np

from ..robust.checkpoint import atomic_savez, atomic_write_bytes

__all__ = ["OnlineJournal"]

_REC_RE = re.compile(r"^chunk-(\d{6,})\.npz$")
_SNAP_RE = re.compile(r"^snapshot-(\d{6,})\.npz$")


class OnlineJournal:
    """Write-ahead journal directory for one :class:`OnlineLoop`.

    Args:
      directory: journal directory (created if missing).  One journal
        per loop; sharing a directory between loops corrupts both.
      snapshot_every: full-state snapshot cadence in chunks.  Smaller
        means faster resume (fewer records to replay) at more write
        cost; records are pruned at each snapshot either way.
      prune: prune records covered by a snapshot and superseded
        snapshots (default).  ``False`` keeps the full history — an
        audit trail of every chunk the loop ever absorbed.
    """

    def __init__(self, directory, *, snapshot_every: int = 16,
                 prune: bool = True):
        if snapshot_every < 1:
            raise ValueError(
                f"snapshot_every must be >= 1, got {snapshot_every}")
        self.directory = str(directory)
        self.snapshot_every = int(snapshot_every)
        self.prune = bool(prune)
        os.makedirs(self.directory, exist_ok=True)
        self.appends = 0
        self.snapshots = 0
        self.withdrawals = 0
        # one writer lock over append/withdraw/snapshot: the snapshot's
        # prune scan must never interleave with an in-flight append —
        # a record that lands mid-scan could otherwise be observed (and
        # unlinked) before the state it journals is snapshotted.  Single-
        # writer loops never contend; sharded/threaded drivers
        # (online/sharding.py) stay safe by construction.
        self._lock = threading.Lock()

    # -- paths ---------------------------------------------------------------

    def _rec_path(self, chunk: int) -> str:
        return os.path.join(self.directory, f"chunk-{chunk:06d}.npz")

    def _snap_path(self, chunk: int) -> str:
        return os.path.join(self.directory, f"snapshot-{chunk:06d}.npz")

    def _scan(self, rx) -> list[tuple[int, str]]:
        out = []
        for name in os.listdir(self.directory):
            m = rx.match(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, name)))
        return sorted(out)

    def records(self, *, after: int = -1) -> list[tuple[int, str]]:
        """``(chunk, path)`` for every journaled record with
        ``chunk > after``, in chunk order."""
        return [(c, p) for c, p in self._scan(_REC_RE) if c > after]

    def latest_snapshot(self) -> Optional[tuple[int, str]]:
        snaps = self._scan(_SNAP_RE)
        return snaps[-1] if snaps else None

    # -- write side ----------------------------------------------------------

    def append(self, chunk: int, tenants, X, y, weights=None,
               offset=None) -> int:
        """Journal one chunk's raw input before it is applied; returns
        the record's byte size.  Inputs are stored exactly as ``step``
        would normalize them, so replay reproduces the same floats."""
        X = np.asarray(X, np.float64)
        y = np.asarray(y, np.float64)
        n = X.shape[0]
        w = (np.ones(n) if weights is None
             else np.asarray(weights, np.float64))
        off = (np.zeros(n) if offset is None
               else np.asarray(offset, np.float64))
        tn = np.asarray([str(t) for t in np.asarray(tenants)])
        with self._lock:
            nbytes = atomic_savez(self._rec_path(int(chunk)),
                                  tenants=tn, X=X, y=y, w=w, off=off)
            self.appends += 1
        return nbytes

    def withdraw(self, chunk: int) -> None:
        """Remove the record of a chunk that was journaled but never
        applied (``step`` rejected its input before any state mutated),
        restoring the WAL invariant that a surviving record is always
        input the live run absorbed — resume must never replay a chunk
        the healthy run refused."""
        with self._lock:
            self._unlink(self._rec_path(int(chunk)))
            self.withdrawals += 1

    @staticmethod
    def load_record(path) -> tuple:
        """``(tenants, X, y, weights, offset)`` from one record file."""
        with np.load(path, allow_pickle=False) as z:
            return (z["tenants"], z["X"], z["y"], z["w"], z["off"])

    def snapshot(self, loop) -> int:
        """Atomically snapshot the loop's full state (serialize v5) at
        its current chunk; prunes covered records and superseded
        snapshots.  Returns the snapshot's byte size."""
        from ..models.serialize import save_model
        chunk = int(loop._chunks)
        buf = io.BytesIO()
        save_model(loop, buf)
        data = buf.getvalue()
        with self._lock:
            # under the writer lock: no append can land between the
            # prune scan and its unlinks, so the only records ever
            # removed are those the snapshot just made redundant
            atomic_write_bytes(self._snap_path(chunk), data)
            self.snapshots += 1
            if self.prune:
                for c, p in self._scan(_REC_RE):
                    # compaction-safety invariant: only records the
                    # snapshot covers (c <= its chunk) are ever removed;
                    # anything newer survives every prune (test-enforced
                    # under a concurrent append/snapshot hammer)
                    if c <= chunk:
                        self._unlink(p)
                for c, p in self._scan(_SNAP_RE):
                    if c < chunk:
                        self._unlink(p)
        return len(data)

    @staticmethod
    def _unlink(path) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass
