"""Exponentially-decayed sufficient statistics for online GLM refresh.

The split-then-combine treatment of PAPERS.md arXiv:2111.00032 represents
a weighted least-squares fit entirely by its Gramian ``G = X'WX`` and
score ``r = X'Wy``: chunks contribute additively, so a model stays
refreshable from O(K·p²) state no matter how many rows have flowed
through.  :class:`OnlineSuffStats` adds the forgetting half: every chunk
tick first decays ALL accumulated state by ``rho`` (one global clock, so
a tenant absent from a chunk still forgets), then adds the chunk's
per-tenant blocks in host float64 in the chunk's left-to-right row order
— the same accumulation-order discipline the streaming fits keep
(PARITY.md), which is what makes a serialized/resumed accumulator
bit-identical to an uninterrupted one.

After C chunks the state equals the sufficient statistics of the
DECAYED-WEIGHT dataset: row i from chunk c carries weight
``w_i * rho^(C - c)``.  For gaussian/identity members that is the whole
fit — ``solve()`` returns the exact WLS coefficients of that dataset in
closed form (tested to 1e-10 against a full refit), no IRLS, no compile.
Non-gaussian families keep the same accumulators for drift statistics
and weight mass, but refresh through a warm-started fleet refit instead
(sparkglm_tpu/online/loop.py): IRLS reweights per iteration, so a single
frozen Gramian cannot carry the fit (the reweighting analyses of
PAPERS.md arXiv:2406.02769).

The class is a registered JAX pytree (arrays are leaves) so state can
ride through ``jax.tree`` utilities and device transfers, but every hot
path here is deliberately host numpy: K small dense p×p solves are a
poor fit for one XLA dispatch and a great fit for LAPACK.
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["OnlineSuffStats"]


@dataclasses.dataclass
class OnlineSuffStats:
    """Decayed per-tenant Gramian/score accumulators (see module doc).

    ``labels`` fixes the tenant order (row k of every array); ``rho`` in
    (0, 1] is the per-chunk decay (1.0 = never forget).  ``G`` (K, p, p),
    ``r`` (K, p) and ``wsum`` (K,) are float64; ``chunks`` counts ticks.
    """

    labels: tuple
    rho: float
    G: np.ndarray
    r: np.ndarray
    wsum: np.ndarray
    chunks: int = 0

    @classmethod
    def init(cls, labels, p: int, *, rho: float = 0.99) -> "OnlineSuffStats":
        labels = tuple(str(t) for t in labels)
        if not labels:
            raise ValueError("need at least one tenant label")
        if len(set(labels)) != len(labels):
            raise ValueError("tenant labels must be unique")
        if not 0.0 < float(rho) <= 1.0:
            raise ValueError(f"decay rho must be in (0, 1], got {rho}")
        K = len(labels)
        return cls(labels=labels, rho=float(rho),
                   G=np.zeros((K, p, p)), r=np.zeros((K, p)),
                   wsum=np.zeros(K), chunks=0)

    @property
    def K(self) -> int:
        return len(self.labels)

    @property
    def p(self) -> int:
        return self.G.shape[-1]

    def _index(self) -> dict:
        return {t: k for k, t in enumerate(self.labels)}

    def update(self, tenants, X, y, *, weights=None, offset=None) -> None:
        """Absorb one chunk: decay EVERY tenant by ``rho``, then add each
        tenant's ``X'WX`` / ``X'W(y - offset)`` block in row order.

        ``tenants`` (n,) labels per row; ``X`` (n, p); ``y`` (n,).
        Accumulation is host float64 regardless of input dtype.  Unknown
        tenant labels raise — the tenant set is fixed between explicit
        :meth:`grow` migrations (it sizes the serving tables, so
        widening must be a deliberate, warmable event, never a silent
        side effect of one chunk).
        """
        X = np.asarray(X, np.float64)
        y = np.asarray(y, np.float64)
        if X.ndim != 2 or X.shape[1] != self.p:
            raise ValueError(
                f"chunk design must be (n, {self.p}), got {X.shape}")
        n = X.shape[0]
        if y.shape != (n,):
            raise ValueError(f"y must be ({n},), got {y.shape}")
        w = (np.ones(n) if weights is None
             else np.asarray(weights, np.float64))
        yv = y if offset is None else y - np.asarray(offset, np.float64)
        tenants = np.asarray(tenants)
        if tenants.shape[0] != n:
            raise ValueError(
                f"{tenants.shape[0]} tenant labels for {n} rows")
        idx = self._index()
        try:
            tidx = np.array([idx[str(t)] for t in tenants], np.int64)
        except KeyError as exc:
            raise KeyError(
                f"unknown tenant {exc.args[0]!r}; online suffstats track "
                f"a fixed tenant set of {self.K}") from None
        # one global tick: every tenant forgets, present in the chunk or
        # not — the decayed-weight dataset semantics above
        if self.rho != 1.0:
            self.G *= self.rho
            self.r *= self.rho
            self.wsum *= self.rho
        # per-tenant blocks in first-appearance order; rows of one tenant
        # accumulate left-to-right inside one einsum (fixed bracketing)
        seen = []
        for k in tidx:
            if k not in seen:
                seen.append(int(k))
        for k in seen:
            m = tidx == k
            Xk, wk, yk = X[m], w[m], yv[m]
            self.G[k] += np.einsum("np,n,nq->pq", Xk, wk, Xk)
            self.r[k] += np.einsum("np,n->p", Xk, wk * yk)
            self.wsum[k] += float(wk.sum())
        self.chunks += 1

    def solve(self, *, jitter: float = 0.0) -> np.ndarray:
        """Closed-form WLS coefficients (K, p) of the decayed dataset —
        the gaussian/identity refresh, no IRLS and no compile.  Tenants
        with no (or fully-decayed) mass, or a singular Gramian, come back
        as NaN rows; the loop skips deploying them."""
        K, p = self.K, self.p
        beta = np.full((K, p), np.nan)
        eye = np.eye(p)
        for k in range(K):
            if self.wsum[k] <= 0.0:
                continue
            Gk = self.G[k] + jitter * eye if jitter else self.G[k]
            try:
                beta[k] = np.linalg.solve(Gk, self.r[k])
            except np.linalg.LinAlgError:
                pass
        return beta

    def grow(self, new_labels) -> "OnlineSuffStats":
        """Migrate to a grown tenant set (serve/growth.py; the tentpole
        answer to "an online system grows tenants by rebuilding the
        family"): returns a NEW accumulator ordered by ``new_labels``
        where every existing tenant's ``G``/``r``/``wsum`` row is COPIED
        — the bytes are moved, never recomputed, so each surviving
        tenant's block is bit-identical to before the migration — and
        every new tenant starts at zero mass (exactly the state it would
        have had if it had been present, absent from every chunk, since
        init; decay of zero is zero).  The global chunk clock carries
        over.  Growth may reorder rows (the family sorts tenants) but
        never drop one."""
        new_labels = tuple(str(t) for t in new_labels)
        if len(set(new_labels)) != len(new_labels):
            raise ValueError("tenant labels must be unique")
        missing = sorted(set(self.labels) - set(new_labels))
        if missing:
            raise ValueError(
                f"growth cannot drop tenants (have accumulated state): "
                f"{missing[:4]}{'...' if len(missing) > 4 else ''}")
        K, p = len(new_labels), self.p
        G = np.zeros((K, p, p))
        r = np.zeros((K, p))
        wsum = np.zeros(K)
        old = self._index()
        for k, t in enumerate(new_labels):
            j = old.get(t)
            if j is not None:
                G[k] = self.G[j]
                r[k] = self.r[j]
                wsum[k] = self.wsum[j]
        return OnlineSuffStats(labels=new_labels, rho=self.rho, G=G, r=r,
                               wsum=wsum, chunks=self.chunks)

    def digest(self) -> str:
        """sha256 over the accumulator bytes (G, r, wsum, chunks) — the
        integrity fingerprint the journal stamps on snapshots and the
        crash-resume tests compare: equal digests mean bit-identical
        statistics."""
        import hashlib
        h = hashlib.sha256()
        h.update(np.ascontiguousarray(self.G, np.float64).tobytes())
        h.update(np.ascontiguousarray(self.r, np.float64).tobytes())
        h.update(np.ascontiguousarray(self.wsum, np.float64).tobytes())
        h.update(str(int(self.chunks)).encode())
        return h.hexdigest()

    # -- persistence (models/serialize.py v5) -------------------------------

    def _export(self) -> tuple[dict, dict]:
        arrays = dict(G=self.G, r=self.r, wsum=self.wsum)
        meta = dict(labels=list(self.labels), rho=self.rho,
                    chunks=int(self.chunks))
        return arrays, meta

    @classmethod
    def _restore(cls, arrays: dict, meta: dict) -> "OnlineSuffStats":
        return cls(labels=tuple(meta["labels"]), rho=float(meta["rho"]),
                   G=np.asarray(arrays["G"], np.float64),
                   r=np.asarray(arrays["r"], np.float64),
                   wsum=np.asarray(arrays["wsum"], np.float64),
                   chunks=int(meta["chunks"]))


def _flatten(ss: OnlineSuffStats):
    return (ss.G, ss.r, ss.wsum), (ss.labels, ss.rho, ss.chunks)


def _unflatten(aux, leaves) -> OnlineSuffStats:
    labels, rho, chunks = aux
    G, r, wsum = leaves
    return OnlineSuffStats(labels=labels, rho=rho, G=G, r=r, wsum=wsum,
                           chunks=chunks)


try:  # register as a pytree; arrays are leaves, identity/decay are aux
    import jax

    jax.tree_util.register_pytree_node(OnlineSuffStats, _flatten,
                                       _unflatten)
except ImportError:  # pragma: no cover - jax is a hard dep in practice
    pass
