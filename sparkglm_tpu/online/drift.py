"""Drift gates over the observability primitives (obs/metrics, obs/trace).

A refreshed model is only worth deploying when the data moved; refitting
every chunk wastes the whole point of sufficient-statistic serving.  The
gate watches two per-tenant distributions, both as the log2 histograms
``obs/metrics.Histogram`` already keeps (no stored samples, bounded
state):

  * score residuals — ``|y - mu|`` per row under the DEPLOYED model;
  * deviance rate — chunk deviance / chunk weight mass, one observation
    per chunk.

The first ``reference_chunks`` chunks fill a reference window which is
then FROZEN.  Live observations fill a rolling window; every
``window_chunks`` chunks the window closes and each tenant's live
distribution is compared against its frozen reference by total-variation
distance (:func:`~sparkglm_tpu.obs.metrics.tv_distance` over the
normalized log2 buckets).  Tenants whose worse metric exceeds
``threshold`` are reported drifted, and one typed ``drift_detected``
trace event (obs/trace.py) is emitted naming them.  After the loop
deploys refreshed members it calls :meth:`rearm` — the reference
re-freezes from fresh observations so the gate measures drift against
the CURRENT champions, not against history.

Everything here is deterministic: same chunks in, same events out —
the e2e test asserts the exact event sequence.
"""

from __future__ import annotations

import math

import numpy as np

from ..obs.metrics import Histogram, tv_distance

__all__ = ["DriftGate"]

_METRICS = ("score_resid", "dev_rate")


def _hist_export(h: Histogram) -> dict:
    return {
        "count": h.count,
        "total": h.total,
        "min": None if h.count == 0 else h.min,
        "max": None if h.count == 0 else h.max,
        "buckets": {str(k): n for k, n in sorted(h.buckets.items())},
    }


def _hist_restore(d: dict) -> Histogram:
    h = Histogram()
    h.count = int(d["count"])
    h.total = float(d["total"])
    h.min = math.inf if d["min"] is None else float(d["min"])
    h.max = -math.inf if d["max"] is None else float(d["max"])
    h.buckets = {int(k): int(n) for k, n in d["buckets"].items()}
    return h


class DriftGate:
    """Frozen-reference vs rolling-window drift detection (module doc).

    Args:
      labels: the fixed tenant order (matches the loop / suffstats).
      threshold: TV distance in [0, 1] above which a tenant counts as
        drifted (on either metric).
      reference_chunks: chunks that fill the frozen reference window.
      window_chunks: live-window length; the gate fires at window close.
      min_count: minimum per-tenant observations in BOTH windows before a
        comparison is trusted (tiny windows make TV noise, not signal).
      tracer: an ``obs/trace.FitTracer`` (or None) for ``drift_detected``.
    """

    def __init__(self, labels, *, threshold: float = 0.25,
                 reference_chunks: int = 4, window_chunks: int = 4,
                 min_count: int = 8, tracer=None):
        if not 0.0 < float(threshold) <= 1.0:
            raise ValueError(
                f"threshold must be in (0, 1], got {threshold}")
        if reference_chunks < 1 or window_chunks < 1:
            raise ValueError("reference_chunks and window_chunks must be "
                             ">= 1")
        self.labels = tuple(str(t) for t in labels)
        self.threshold = float(threshold)
        self.reference_chunks = int(reference_chunks)
        self.window_chunks = int(window_chunks)
        self.min_count = int(min_count)
        self.tracer = tracer
        self._ref_filled = 0     # chunks absorbed into the reference
        self._live_filled = 0    # chunks in the current live window
        self._ref = {t: {m: Histogram() for m in _METRICS}
                     for t in self.labels}
        self._live = {t: {m: Histogram() for m in _METRICS}
                      for t in self.labels}

    # -- observation ---------------------------------------------------------

    @property
    def reference_frozen(self) -> bool:
        return self._ref_filled >= self.reference_chunks

    def observe_chunk(self, per_tenant: dict) -> tuple[str, ...]:
        """Absorb one chunk's statistics and advance the window clock.

        ``per_tenant`` maps tenant label -> ``(abs_resid, dev, wt_sum)``
        where ``abs_resid`` is the row vector of ``|y - mu|`` under the
        deployed model and ``dev``/``wt_sum`` are the chunk's deviance
        and weight mass for that tenant.  Returns the drifted tenants
        (empty unless this chunk closes a live window that trips the
        gate).
        """
        target = self._ref if not self.reference_frozen else self._live
        for tenant, (resid, dev, wt_sum) in per_tenant.items():
            hs = target[str(tenant)]
            for v in np.asarray(resid, np.float64):
                hs["score_resid"].observe(abs(float(v)))
            if wt_sum > 0:
                hs["dev_rate"].observe(float(dev) / float(wt_sum))
        if not self.reference_frozen:
            self._ref_filled += 1
            return ()
        self._live_filled += 1
        if self._live_filled < self.window_chunks:
            return ()
        return self._close_window()

    def _close_window(self) -> tuple[str, ...]:
        drifted, tv_max = [], 0.0
        for t in self.labels:
            worst = 0.0
            for m in _METRICS:
                ref, live = self._ref[t][m], self._live[t][m]
                if (ref.count < self.min_count
                        or live.count < self.min_count):
                    continue
                worst = max(worst, tv_distance(ref, live))
            tv_max = max(tv_max, worst)
            if worst > self.threshold:
                drifted.append(t)
        # the live window always resets at close; the reference stays
        # frozen until rearm()
        self._live = {t: {m: Histogram() for m in _METRICS}
                      for t in self.labels}
        self._live_filled = 0
        if self.tracer is not None and self.tracer.metrics is not None:
            # exported gauge: the worst windowed TV distance at every
            # window close, drifted or not — the dashboard's early-warning
            # line under the threshold
            m = self.tracer.metrics
            m.gauge("online.drift.tv_max").set(tv_max)
            m.gauge("online.drift.tenants_drifted").set(float(len(drifted)))
        if drifted and self.tracer is not None:
            self.tracer.emit("drift_detected", tenants=len(drifted),
                             first=drifted[0], tv_max=round(tv_max, 6),
                             threshold=self.threshold)
        return tuple(drifted)

    def grow(self, new_labels) -> None:
        """Extend the gate to a grown tenant set in place (the
        serve/growth.py migration): existing tenants keep their frozen
        reference and live histograms untouched; new tenants start with
        empty windows and begin accumulating on the next chunk.  The
        shared window clocks carry over, so the gate keeps firing on the
        same chunk boundaries as an ungrown run."""
        new_labels = tuple(str(t) for t in new_labels)
        missing = sorted(set(self.labels) - set(new_labels))
        if missing:
            raise ValueError(
                f"growth cannot drop tenants: {missing[:4]}"
                f"{'...' if len(missing) > 4 else ''}")
        for t in new_labels:
            if t not in self._ref:
                self._ref[t] = {m: Histogram() for m in _METRICS}
                self._live[t] = {m: Histogram() for m in _METRICS}
        self.labels = new_labels

    def rearm(self) -> None:
        """Forget the frozen reference and refill it from the next
        ``reference_chunks`` chunks — called after a deploy so drift is
        measured against the new champions."""
        self._ref = {t: {m: Histogram() for m in _METRICS}
                     for t in self.labels}
        self._live = {t: {m: Histogram() for m in _METRICS}
                      for t in self.labels}
        self._ref_filled = 0
        self._live_filled = 0

    # -- persistence (models/serialize.py v5) -------------------------------

    def _export(self) -> dict:
        return dict(
            threshold=self.threshold,
            reference_chunks=self.reference_chunks,
            window_chunks=self.window_chunks,
            min_count=self.min_count,
            ref_filled=self._ref_filled,
            live_filled=self._live_filled,
            ref={t: {m: _hist_export(self._ref[t][m]) for m in _METRICS}
                 for t in self.labels},
            live={t: {m: _hist_export(self._live[t][m]) for m in _METRICS}
                  for t in self.labels})

    @classmethod
    def _restore(cls, labels, state: dict, *, tracer=None) -> "DriftGate":
        gate = cls(labels, threshold=state["threshold"],
                   reference_chunks=state["reference_chunks"],
                   window_chunks=state["window_chunks"],
                   min_count=state["min_count"], tracer=tracer)
        gate._ref_filled = int(state["ref_filled"])
        gate._live_filled = int(state["live_filled"])
        gate._ref = {t: {m: _hist_restore(state["ref"][t][m])
                         for m in _METRICS} for t in gate.labels}
        gate._live = {t: {m: _hist_restore(state["live"][t][m])
                          for m in _METRICS} for t in gate.labels}
        return gate
