"""Number/table formatting for R-style summaries.

Mirrors the reference's print helpers — ``roundDigits``/``sigDigits``
(/root/reference/src/main/scala/com/Alteryx/sparkGLM/utils.scala:146-169) and
the fixed-width coefficient table assembly in ``SummaryLM``
(LM.scala:100-114) / ``GLM.summary`` (GLM.scala:1009-1024).
"""

from __future__ import annotations

import math

import numpy as np


def sig_digits(x: float, digits: int = 4) -> str:
    """Significant-digit formatting like R's ``signif`` (utils.scala:157-169)."""
    if x is None or (isinstance(x, float) and (math.isnan(x) or math.isinf(x))):
        return str(x)
    if x == 0:
        return "0"
    mag = math.floor(math.log10(abs(x)))
    if mag < -4 or mag >= digits + 3:
        return f"{x:.{max(digits - 1, 0)}e}"
    decimals = max(digits - 1 - mag, 0)
    s = f"{x:.{decimals}f}"
    return s


def round_digits(x: float, digits: int = 4) -> str:
    """Fixed decimal rounding (utils.scala:146-154)."""
    return f"{x:.{digits}f}"


def p_stars(p: float) -> str:
    """R's significance codes."""
    if p < 0.001:
        return "***"
    if p < 0.01:
        return "**"
    if p < 0.05:
        return "*"
    if p < 0.1:
        return "."
    return " "


def coef_table(
    names,
    columns: dict[str, np.ndarray],
    *,
    stars_from: str | None = None,
    digits: int = 4,
) -> str:
    """Fixed-width coefficient table: one row per name, one column per stat."""
    headers = list(columns)
    cells = {
        h: [sig_digits(float(v), digits) for v in columns[h]] for h in headers
    }
    name_w = max([len(str(n)) for n in names] + [0])
    widths = {h: max([len(h)] + [len(c) for c in cells[h]]) for h in headers}
    lines = [" " * name_w + "  " + "  ".join(h.rjust(widths[h]) for h in headers)]
    for i, nm in enumerate(names):
        row = str(nm).ljust(name_w) + "  " + "  ".join(
            cells[h][i].rjust(widths[h]) for h in headers)
        if stars_from is not None:
            row += " " + p_stars(float(columns[stars_from][i]))
        lines.append(row)
    if stars_from is not None:
        lines.append("---")
        lines.append("Signif. codes:  0 '***' 0.001 '**' 0.01 '*' 0.05 '.' 0.1 ' ' 1")
    return "\n".join(lines)
