"""Profiling / tracing hooks.

The reference has NO instrumentation at all — its only progress signal is
the optional per-iteration ``iter\\tddev`` print (SURVEY.md §5 "Tracing /
profiling: none").  We carry that trace (``verbose=True`` on the fits) and
add what a TPU workload actually needs: ``jax.profiler`` capture around a
region, viewable in TensorBoard/Perfetto, plus a simple wall-clock timer
that forces device completion (host read) so numbers are honest even on
asynchronous dispatch backends.
"""

from __future__ import annotations

import contextlib
import time

import jax


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a ``jax.profiler`` trace of the enclosed region::

        with sg.profiling.trace("/tmp/jax-trace"):
            sg.glm_fit(X, y, family="binomial")
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Timer:
    """Wall-clock timing that blocks on device results.

    ``jax.block_until_ready`` can be unreliable over remote-device
    transports, so ``stop(out)`` forces a host read of one element of the
    result before taking the time.
    """

    def __init__(self):
        self.t0 = None
        self.elapsed = None

    def start(self) -> "Timer":
        self.t0 = time.perf_counter()
        return self

    def stop(self, out=None) -> float:
        if out is not None:
            # sync EVERY leaf: separately dispatched results complete
            # independently, so reading one is not enough
            for leaf in jax.tree.leaves(out):
                if hasattr(leaf, "ravel") and getattr(leaf, "size", 0):
                    float(leaf.ravel()[0])
        self.elapsed = time.perf_counter() - self.t0
        return self.elapsed
