"""Preemption-safe checkpoint/resume of streaming fit state.

A streaming fit carries tiny state between passes — for GLM IRLS the
coefficient vector, the iteration count and the deviance measured by the
last pass; for the one-shot LM the accumulated Gramian — so a preempted
multi-hour fit over a fixed source is resumable from a few-hundred-byte
file.  The contract mirrors the resident fit's ``checkpoint_every``/
``beta0`` pair (``models/glm.py``): the streaming GLM saves after every
completed IRLS iteration, and ``resume=`` restores (beta, iteration,
deviance baseline) and continues the SAME pass trajectory — passes are
deterministic given the source, so the resumed run's remaining iterations
are bit-for-bit the iterations the uninterrupted run would have made.

Durability is by atomic rename: state is serialized to a temp sibling and
``os.replace``d over the target, so a preemption mid-write leaves either
the previous complete checkpoint or the new complete checkpoint, never a
torn file.

Identity is by source fingerprint: the checkpoint records the streaming
layer's ``_fingerprint`` of the first chunk (shape + corner samples) plus
the design width; resume validates both and refuses with ``ValueError``
when the source does not look like the one that produced the checkpoint.
"""

from __future__ import annotations

import io
import os
import tempfile

import numpy as np

_FORMAT = 1
_RESERVED = ("format", "kind", "fingerprint", "p")


def _emit(kind: str, **fields) -> None:
    """Durability events flow into whatever fit is running (the ambient
    tracer, obs/trace.py); lazy import keeps robust importable standalone."""
    from ..obs.trace import emit_ambient
    emit_ambient(kind, **fields)


def atomic_write_bytes(path: str | os.PathLike, data: bytes) -> None:
    """Durably write ``data`` at ``path`` by temp-sibling + fsync +
    ``os.replace`` — a crash mid-write leaves either the previous complete
    file or the new complete file, never a torn one.  The write-rename
    primitive under every durable artifact here: checkpoints, flight
    records, and the online write-ahead journal (online/journal.py)."""
    path = os.fspath(path)
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".ckpt-", suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_savez(path: str | os.PathLike, **arrays) -> int:
    """``np.savez`` through :func:`atomic_write_bytes`; returns the record
    size in bytes (serialization happens in memory first — journal/
    checkpoint records are tiny relative to the data they make durable)."""
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    atomic_write_bytes(path, buf.getvalue())
    return buf.tell()


def _fp_array(fingerprint) -> np.ndarray:
    """Fingerprint tuples may contain None for absent weight/offset corner
    samples (``streaming._fingerprint``); encode as NaN so the record is a
    plain f64 vector (compared with equal_nan=True)."""
    return np.asarray([np.nan if v is None else float(v)
                       for v in tuple(fingerprint)], dtype=np.float64)


class CheckpointManager:
    """Atomic save/load of streaming-fit state at ``path``.

    The serialized record holds a format version, a model-kind tag
    (``"glm"``/``"lm"``), the chunk-source fingerprint, the design width
    ``p``, and an arbitrary payload of numpy-convertible values (the GLM
    trajectory state or the LM accumulators).
    """

    def __init__(self, path: str | os.PathLike):
        self.path = os.fspath(path)

    def exists(self) -> bool:
        return os.path.exists(self.path)

    def save(self, *, kind: str, fingerprint, p: int, **payload) -> None:
        for k in payload:
            if k in _RESERVED:
                raise ValueError(f"payload key {k!r} is reserved")
        nbytes = atomic_savez(
            self.path,
            format=np.int64(_FORMAT),
            kind=np.bytes_(kind.encode()),
            fingerprint=_fp_array(fingerprint),
            p=np.int64(p),
            **{k: np.asarray(v) for k, v in payload.items()})
        # emitted only after the atomic rename: the event means "this
        # checkpoint is durable", not "a write was attempted"
        fields = {"path": self.path, "model": kind, "bytes": nbytes}
        if "iters" in payload:
            fields["iters"] = int(np.asarray(payload["iters"]))
        _emit("checkpoint_write", **fields)

    def load(self) -> dict:
        with np.load(self.path) as z:
            fmt = int(z["format"])
            if fmt != _FORMAT:
                raise ValueError(
                    f"checkpoint {self.path!r} has format {fmt}; this build "
                    f"reads format {_FORMAT}")
            out = {
                "kind": bytes(z["kind"]).decode(),
                "fingerprint": np.asarray(z["fingerprint"], np.float64),
                "p": int(z["p"]),
            }
            for k in z.files:
                if k not in _RESERVED:
                    out[k] = np.asarray(z[k])
            return out

    def validate(self, state: dict, *, kind: str, fingerprint, p: int) -> None:
        """Refuse a checkpoint that does not match the live source/model."""
        if state["kind"] != kind:
            raise ValueError(
                f"checkpoint {self.path!r} was written by a "
                f"{state['kind']!r} fit; cannot resume a {kind!r} fit from it")
        if state["p"] != p:
            raise ValueError(
                f"checkpoint {self.path!r} has {state['p']} coefficients but "
                f"the source yields {p}; refusing to resume from a different "
                f"design")
        want = np.asarray(state["fingerprint"], np.float64)
        got = _fp_array(fingerprint)
        if want.shape != got.shape or not np.array_equal(
                want, got, equal_nan=True):
            raise ValueError(
                f"checkpoint {self.path!r} does not match this chunk source "
                f"(first-chunk fingerprint differs); resuming against a "
                f"different source would silently corrupt the trajectory — "
                f"delete the checkpoint (or drop resume=) to start over")
        # emitted on ACCEPTED resumes only — a rejected checkpoint raises
        # above and the fit never continues from it
        fields = {"path": self.path, "model": kind, "p": int(p)}
        if "iters" in state:
            fields["iters"] = int(np.asarray(state["iters"]))
        _emit("resume", **fields)

    def remove(self) -> None:
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass


def as_checkpoint(spec) -> "CheckpointManager | None":
    """Coerce a user-facing ``checkpoint=``/``resume=`` value: None (and
    False) pass through as None, True is rejected here (it means "use the
    checkpoint= target" and is resolved by the caller), a path becomes a
    manager, a manager is returned as-is."""
    if spec is None or spec is False or isinstance(spec, CheckpointManager):
        return spec or None
    if spec is True:
        raise ValueError("resume=True needs a checkpoint= target to resume from")
    return CheckpointManager(spec)
