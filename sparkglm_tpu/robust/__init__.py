"""Fault tolerance for streaming fits on preemptible fleets.

The reference leans on Spark's lineage recovery for every failure mode
(SURVEY.md §2.4); this package makes each mode EXPLICIT instead:

  * :mod:`.retry` — typed transient/fatal source errors and a capped
    exponential-backoff retry policy with deterministic jitter, applied to
    chunk materialization in the streaming fits and to the CSV/Parquet
    readers (``data/io.py`` / ``data/parquet.py``).
  * :mod:`.checkpoint` — preemption-safe atomic checkpoint/resume of
    streaming IRLS state (beta, iteration, deviance baseline, chunk-source
    fingerprint); ``glm_fit_streaming(checkpoint=, resume=)`` continues an
    interrupted pass trajectory bit-for-bit.
  * :mod:`.faults` — a seeded fault-injection harness wrapping any chunk
    source or reader with scheduled transient/fatal errors and simulated
    preemptions; drives the test suite and ``bench.py``'s recovery-overhead
    measurement.

Step-halving recovery for diverging IRLS steps lives in the kernels
themselves (``models/glm.py::_irls_kernel`` / ``_irls_fused_kernel``) —
it is device-side state, not a host wrapper.
"""

from .checkpoint import (CheckpointManager, as_checkpoint, atomic_savez,
                         atomic_write_bytes)
from .faults import FaultPlan, SimulatedPreemption, faulty_reader, faulty_source
from .retry import (DeadlineExceeded, FatalSourceError, Overloaded,
                    ReplicaUnavailable, RetryBudgetExhausted, RetryingSource,
                    RetryPolicy, TransientSourceError, call_with_retry,
                    retrying_source)

__all__ = [
    "TransientSourceError", "FatalSourceError", "Overloaded",
    "DeadlineExceeded", "ReplicaUnavailable",
    "RetryBudgetExhausted",
    "RetryPolicy", "RetryingSource", "call_with_retry", "retrying_source",
    "CheckpointManager", "as_checkpoint",
    "atomic_write_bytes", "atomic_savez",
    "FaultPlan", "SimulatedPreemption", "faulty_source", "faulty_reader",
]
