"""Seeded fault injection for chunk sources and readers.

Robustness code that is only exercised by real outages is untested code.
This module wraps any chunk source (or reader callable) with a
deterministic, seeded schedule of failures so the retry/checkpoint paths
run in CI on every ``make robust``:

  * scheduled TRANSIENT errors — raised on chosen (pass, chunk) touches,
    each fault fires once and then that touch succeeds on retry, modelling
    a flaky read;
  * scheduled FATAL errors — always re-raised, modelling corrupt data;
  * simulated PREEMPTION — :class:`SimulatedPreemption` (a ``BaseException``
    like a real ``SystemExit``, so retry code cannot eat it) raised on the
    n-th touch, killing the fit mid-pass to exercise checkpoint/resume;
  * scheduled WORKER KILLS — preemptions addressed by ``(pass, chunk)``
    coordinates instead of the touch counter (``preempt_chunk_at``), each
    firing once, so the elastic engine's kill-resume-recover loop is
    reproducible independent of how many retries shifted the touch stream.

Counting is by TOUCH: every materialization attempt (chunk yielded, thunk
called, reader invoked) increments one shared counter, so a schedule like
``transient_at=(3, 7)`` is reproducible no matter how the touches spread
over passes.  Probabilistic schedules draw from ``numpy`` Generators seeded
from ``FaultPlan.seed`` — same seed, same outage.

``bench.py`` uses the same plan to measure recovery overhead: fit a
streaming GLM with and without injected transients and report the delta.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
from typing import Callable, Sequence

import numpy as np

from .retry import (FatalSourceError, ReplicaUnavailable,
                    TransientSourceError)


class SimulatedPreemption(BaseException):
    """An injected preemption.  Deliberately a ``BaseException`` (like
    ``KeyboardInterrupt``/``SystemExit``, which real preemption handlers
    deliver) so neither the retry layer nor a broad ``except Exception``
    can swallow it."""


@dataclasses.dataclass
class FaultPlan:
    """A deterministic schedule of injected failures.

    ``transient_at``/``fatal_at``/``preempt_at`` are 0-based touch indices
    (a touch = one materialization attempt anywhere in the wrapped source
    or reader).  A transient fault at touch ``t`` fires only the FIRST time
    touch index ``t`` is reached — the retried attempt is a new touch and
    proceeds — while fatal faults and preemptions always fire.
    ``p_transient`` adds seeded random transients on top of the scheduled
    ones.  One plan instance carries one mutable touch counter; share the
    instance between a source and a reader to schedule across both, or use
    fresh instances for independent schedules.

    ``preempt_chunk_at`` is the WORKER-KILL schedule the elastic engine
    tests with: ``(pass, chunk)`` pairs addressed by the wrapped source's
    own counters — ``pass`` counts openings of the wrapped source over
    the plan's lifetime (one per streaming pass; monotonic across a kill
    and restart, so a resumed fit's passes get fresh indices and cannot
    re-die at the old coordinate), ``chunk`` counts chunks within that
    pass.  Unlike the touch-indexed ``preempt_at`` it is position-stable
    under retries (a retried touch shifts every later touch index but no
    chunk index) and each pair additionally fires ONCE, so the schedule
    stays a finite set of kills even when coordinates recur after
    :meth:`reset`.

    SERVING-TIME kinds are addressed by ``(replica, dispatch)`` — the
    plan keeps one dispatch ordinal PER REPLICA (thread-safe: replica
    workers touch concurrently), so a schedule names "replica 0's third
    batch" no matter how batches interleave across replicas:

      * ``replica_error_at`` — that dispatch raises
        :class:`~.retry.ReplicaUnavailable` (fires once; the replica is
        flaky but alive, a later probe succeeds);
      * ``replica_dead_from`` — EVERY dispatch on that replica from the
        given ordinal onward fails (a killed replica: probes keep
        failing, the breaker stays open);
      * ``replica_slow_at`` — the dispatch sleeps ``slow_s`` before
        proceeding (straggler; the hedge budget fires, both calls
        complete, first result wins);
      * ``replica_hang_at`` — the dispatch sleeps ``hang_s`` (hung but
        alive: the engine's watchdog deadline fires and abandons the
        call; its late result is discarded by first-result-wins).

    ``kill_chunk_at`` is the ONLINE-LOOP kill schedule: at those chunk
    boundaries :meth:`on_online_chunk` SIGKILLs the current process — a
    real, unhandleable death for exercising the write-ahead journal's
    crash/resume path.  Only call it from an expendable subprocess.

    ``ingest_worker_dead_at`` is the INGEST-WORKER kill schedule:
    ``(worker, k)`` pairs meaning "ingest worker ``worker`` dies
    (``os._exit``, no cleanup — a SIGKILL/OOM stand-in) just before its
    ``k``-th assigned read".  :meth:`on_ingest_read` runs INSIDE the
    forked reader process (``data/ingest.py``), so the death is a real
    process death: the consumer's queue starves, its liveness check
    fires, and the re-read recovery path runs exactly as it would in
    production.  Safe by construction — only the expendable worker dies.

    ENGINE-TIER kinds are addressed by ``(engine, submit)`` — one submit
    ordinal PER ENGINE, mirroring the per-replica dispatch ordinals one
    level up (serve/pool.py's multi-engine tier):

      * ``engine_error_at`` — that submission raises
        :class:`~.retry.ReplicaUnavailable` (fires once; the engine
        hiccuped, the pool re-routes and a later probe readmits);
      * ``engine_dead_from`` — EVERY submission to that engine from the
        given ordinal onward fails (a crashed engine process: the pool's
        health breaker ejects it and its traffic re-routes to the
        survivors with zero lost requests).
    """

    transient_at: Sequence[int] = ()
    fatal_at: Sequence[int] = ()
    preempt_at: Sequence[int] = ()
    preempt_chunk_at: Sequence[tuple] = ()
    p_transient: float = 0.0
    seed: int = 0
    replica_error_at: Sequence[tuple] = ()
    replica_dead_from: Sequence[tuple] = ()
    replica_slow_at: Sequence[tuple] = ()
    replica_hang_at: Sequence[tuple] = ()
    slow_s: float = 0.25
    hang_s: float = 30.0
    kill_chunk_at: Sequence[int] = ()
    engine_error_at: Sequence[tuple] = ()
    engine_dead_from: Sequence[tuple] = ()
    ingest_worker_dead_at: Sequence[tuple] = ()

    def __post_init__(self):
        self._touch = 0
        self._passes = 0
        self._fired = set()
        self._preempt_pairs = {tuple(int(v) for v in pc)
                               for pc in self.preempt_chunk_at}
        self._err_pairs = {tuple(int(v) for v in rc)
                           for rc in self.replica_error_at}
        self._slow_pairs = {tuple(int(v) for v in rc)
                            for rc in self.replica_slow_at}
        self._hang_pairs = {tuple(int(v) for v in rc)
                            for rc in self.replica_hang_at}
        self._dead_from = {}
        for r, k in self.replica_dead_from:
            r, k = int(r), int(k)
            self._dead_from[r] = min(k, self._dead_from.get(r, k))
        self._dispatches = {}
        self._eng_err_pairs = {tuple(int(v) for v in ec)
                               for ec in self.engine_error_at}
        self._eng_dead_from = {}
        for e, k in self.engine_dead_from:
            e, k = int(e), int(k)
            self._eng_dead_from[e] = min(k, self._eng_dead_from.get(e, k))
        self._eng_submits = {}
        self._ingest_dead_pairs = {tuple(int(v) for v in wc)
                                   for wc in self.ingest_worker_dead_at}
        self._lock = threading.Lock()
        self._rng = np.random.default_rng(self.seed)
        self.faults_fired = 0

    def reset(self) -> None:
        """Rewind the schedule (fresh touch counter, RNG, fired-set)."""
        self.__post_init__()

    def on_touch(self) -> None:
        """Advance the touch counter; raise if this touch is scheduled."""
        t = self._touch
        self._touch += 1
        if t in self.preempt_at:
            self.faults_fired += 1
            raise SimulatedPreemption(f"injected preemption at touch {t}")
        if t in self.fatal_at:
            self.faults_fired += 1
            raise FatalSourceError(f"injected fatal error at touch {t}")
        if t in self.transient_at and t not in self._fired:
            self._fired.add(t)
            self.faults_fired += 1
            raise TransientSourceError(f"injected transient error at touch {t}")
        if self.p_transient > 0.0 and self._rng.random() < self.p_transient:
            self.faults_fired += 1
            raise TransientSourceError(f"injected random transient at touch {t}")

    def on_dispatch(self, replica: int) -> None:
        """One replica-call touch: advance ``replica``'s dispatch ordinal
        and fire whatever the serving schedule names at that coordinate.
        Called from the engine's replica worker thread, BEFORE scoring, so
        an injected failure looks exactly like a failing device call."""
        replica = int(replica)
        with self._lock:
            k = self._dispatches.get(replica, 0)
            self._dispatches[replica] = k + 1
            key = (replica, k)
            dead = (replica in self._dead_from
                    and k >= self._dead_from[replica])
            err = key in self._err_pairs and ("err", key) not in self._fired
            if err:
                self._fired.add(("err", key))
            slow = key in self._slow_pairs and ("slow", key) not in self._fired
            if slow:
                self._fired.add(("slow", key))
            hang = key in self._hang_pairs and ("hang", key) not in self._fired
            if hang:
                self._fired.add(("hang", key))
            if dead or err or slow or hang:
                self.faults_fired += 1
        if hang:
            time.sleep(self.hang_s)
            return
        if slow:
            time.sleep(self.slow_s)
            return
        if dead or err:
            raise ReplicaUnavailable(
                f"injected replica failure: replica {replica}, dispatch {k}"
                + (" (dead)" if dead else ""))

    def on_engine_submit(self, engine: int) -> None:
        """One engine-tier submission touch: advance ``engine``'s submit
        ordinal and fire whatever the engine schedule names there.
        Called by the pool's dispatch path BEFORE handing the request to
        the engine, so an injected failure looks exactly like a dead or
        flaky engine process refusing work."""
        engine = int(engine)
        with self._lock:
            k = self._eng_submits.get(engine, 0)
            self._eng_submits[engine] = k + 1
            key = (engine, k)
            dead = (engine in self._eng_dead_from
                    and k >= self._eng_dead_from[engine])
            err = (key in self._eng_err_pairs
                   and ("eng_err", key) not in self._fired)
            if err:
                self._fired.add(("eng_err", key))
            if dead or err:
                self.faults_fired += 1
        if dead or err:
            raise ReplicaUnavailable(
                f"injected engine failure: engine {engine}, submit {k}"
                + (" (dead)" if dead else ""))

    def on_online_chunk(self, chunk_idx: int) -> None:
        """Fire a scheduled process kill at an online-loop chunk boundary.
        SIGKILL — no cleanup, no exception, no atexit: the journal's
        durability is all that survives.  Subprocess use only."""
        if int(chunk_idx) in set(int(c) for c in self.kill_chunk_at):
            os.kill(os.getpid(), signal.SIGKILL)

    def on_ingest_read(self, worker: int, k: int) -> None:
        """Die hard if ingest worker ``worker``'s ``k``-th read is
        scheduled.  ``os._exit`` — no exception, no finally blocks, no
        queue flush: the consumer must detect the death from the outside,
        like a real OOM-killed parse worker.  Runs in the forked worker
        (the plan object is a fork-time copy; no once-firing bookkeeping
        is needed because the process does not survive to re-fire)."""
        if (int(worker), int(k)) in self._ingest_dead_pairs:
            os._exit(17)

    def on_chunk_touch(self, pass_idx: int, chunk_idx: int) -> None:
        """Fire a scheduled worker kill at ``(pass_idx, chunk_idx)`` — once."""
        key = (pass_idx, chunk_idx)
        if key in self._preempt_pairs and key not in self._fired:
            self._fired.add(key)
            self.faults_fired += 1
            raise SimulatedPreemption(
                f"injected worker kill at pass {pass_idx}, chunk {chunk_idx}")


def faulty_source(chunks: Callable, plan: FaultPlan) -> Callable:
    """Wrap a chunk-source factory so each chunk delivery is a fault touch.

    Lazy chunks stay lazy: a thunk's touch happens when the THUNK is
    called, not when it is yielded, matching where a real source fails.
    Retries re-touch, so one retry consumes one more schedule slot.
    """

    def gen():
        pass_idx = plan._passes
        plan._passes += 1
        for chunk_idx, raw in enumerate(chunks()):
            if callable(raw):
                def lazy(thunk=raw, pi=pass_idx, ci=chunk_idx):
                    plan.on_chunk_touch(pi, ci)
                    plan.on_touch()
                    return thunk()
                yield lazy
            else:
                plan.on_chunk_touch(pass_idx, chunk_idx)
                plan.on_touch()
                yield raw

    return gen


def faulty_reader(reader: Callable, plan: FaultPlan) -> Callable:
    """Wrap a reader callable (``read_csv``-like) so each invocation is a
    fault touch, for exercising ``retry=`` on the IO layer."""

    def wrapped(*args, **kwargs):
        plan.on_touch()
        return reader(*args, **kwargs)

    return wrapped
