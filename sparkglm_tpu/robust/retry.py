"""Retry/backoff policy for transient chunk-source and reader failures.

A multi-pass streaming fit touches its source O(iterations x chunks) times;
at fleet scale some of those touches WILL fail transiently (an object-store
503, a flaky NFS read, a preempted parse worker).  Today's behavior — any
exception kills the whole fit from iteration zero — is the single biggest
gap between the streaming path and the ROADMAP's production north star.

The model here is explicit and typed:

  * :class:`TransientSourceError` — raise this (or register exception types
    via ``RetryPolicy.retryable``) for failures worth retrying.
  * :class:`FatalSourceError` — never retried, even if its cause would be:
    wrap a retryable type in this to force a hard stop.
  * :class:`RetryPolicy` — capped exponential backoff with DETERMINISTIC
    jitter (hash-seeded, so two runs of the same fit sleep the same
    schedule — reproducibility is a feature, thundering-herd avoidance
    still works because the seed folds in the retry key), plus a per-pass
    retry budget.

Multi-process coherence: a retry is process-local host work between
collectives, so it needs no coordination while it is being attempted; a
retry budget that EXHAUSTS raises, and that error reaches the other
processes through the streaming layer's ``_sync_errors`` flag exchange —
retry decisions are synchronized exactly like errors are (see
``models/streaming.py``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Callable, Sequence


class TransientSourceError(Exception):
    """A chunk-source/reader failure worth retrying (flaky IO, a 5xx from
    object storage, a preempted parse worker).  Always classified
    transient by every :class:`RetryPolicy`."""


class FatalSourceError(Exception):
    """A failure that must NOT be retried even when its cause is a type the
    policy would otherwise classify transient (e.g. corrupt data discovered
    during a read)."""


class Overloaded(TransientSourceError):
    """The serving admission queue is full (sparkglm_tpu/serve/batching.py).

    Transient BY TYPE: backpressure clears as the micro-batcher drains, so
    a client-side :class:`RetryPolicy` retries it with backoff like any
    flaky-source failure — one classification scheme for fit-time and
    serve-time faults.

    ``retry_after_s`` is a drain-rate hint: the admitting engine computes
    it from its measured throughput (queued rows / rows-per-second served
    so far), so a client that honors it backs off just long enough for the
    queue to clear instead of guessing.  ``None`` when the engine has not
    served anything yet (no rate to measure)."""

    def __init__(self, message: str, *, retry_after_s: "float | None" = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class DeadlineExceeded(TimeoutError):
    """A serving request's ``deadline=`` elapsed before it was dispatched,
    or its caller abandoned it (``score(timeout=)`` / ``asubmit(timeout=)``).

    The request is CANCELLED OUT OF THE QUEUE — it is never scored, so a
    caller that already gave up does not burn replica time (dead-work
    shedding happens at batch-formation time, sparkglm_tpu/serve/
    async_engine.py).  A ``TimeoutError`` subtype so existing timeout
    handling (``concurrent.futures`` raises ``TimeoutError`` from
    ``future.result(timeout)``) catches it unchanged."""


class ReplicaUnavailable(TransientSourceError):
    """A replica call failed or exceeded its watchdog deadline (hung).

    Typed circuit-breaker fuel (sparkglm_tpu/serve/health.py): consecutive
    ``ReplicaUnavailable`` outcomes trip a replica's breaker open
    (ejection); the engine re-dispatches the batch to a surviving replica,
    so requests only ever see this when EVERY dispatch attempt failed.
    Transient by type — the breaker's half-open probe decides recovery."""


class RetryBudgetExhausted(RuntimeError):
    """The per-pass retry budget ran out; carries the last transient error
    as ``__cause__``."""


def _default_sleep(seconds: float) -> None:
    if seconds > 0:
        time.sleep(seconds)


def _emit(kind: str, **fields) -> None:
    """Fault events flow into whatever fit is running (the ambient tracer,
    obs/trace.py); lazy import keeps robust importable standalone."""
    from ..obs.trace import emit_ambient
    emit_ambient(kind, **fields)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff with deterministic jitter.

    ``delay(attempt, key)`` = min(base * 2^attempt, cap) * (1 + jitter*u)
    where u in [-1, 1) is derived from sha256(seed, key, attempt) — fully
    deterministic for a given (seed, key) so recovery runs are
    reproducible, yet de-correlated across chunks/processes (fold the
    chunk index or process index into ``key``).

    ``budget`` is the PER-PASS retry allowance: each streaming pass gets a
    fresh :class:`RetryBudget` of this size, so a long fit cannot bleed to
    death one retry at a time across hundreds of passes, while a genuinely
    dead source still fails fast within one pass.
    """

    max_retries: int = 4          # per failing call
    budget: int = 16              # per pass, across all calls
    base_delay: float = 0.05      # seconds
    max_delay: float = 8.0        # backoff cap
    jitter: float = 0.25          # +/- fraction of the backoff delay
    seed: int = 0
    # exception types classified transient IN ADDITION to
    # TransientSourceError; OSError covers flaky file/network IO
    retryable: tuple = (OSError,)
    sleep: Callable[[float], None] = _default_sleep

    def is_transient(self, exc: BaseException) -> bool:
        if isinstance(exc, FatalSourceError):
            return False
        if isinstance(exc, TransientSourceError):
            return True
        return isinstance(exc, tuple(self.retryable))

    def delay(self, attempt: int, key: object = "") -> float:
        raw = min(self.base_delay * (2.0 ** attempt), self.max_delay)
        h = hashlib.sha256(
            f"{self.seed}|{key}|{attempt}".encode()).digest()
        u = int.from_bytes(h[:8], "big") / float(1 << 64)  # [0, 1)
        return raw * (1.0 + self.jitter * (2.0 * u - 1.0))

    def new_budget(self) -> "RetryBudget":
        return RetryBudget(self.budget)


class RetryBudget:
    """Mutable retry allowance shared by every retried call in one scope.

    The streaming fits give each PASS a fresh budget; the elastic
    scheduler shares ONE instance across every shard's restart attempts
    so a fleet-wide outage fails fast instead of each shard burning a
    private allowance (``sparkglm_tpu/elastic/scheduler.py``).
    """

    def __init__(self, total: int):
        self.total = int(total)
        self.spent = 0

    def remaining(self) -> int:
        return max(0, self.total - self.spent)

    def spend(self, exc: BaseException) -> None:
        self.spent += 1
        if self.spent > self.total:
            _emit("budget_exhausted", total=self.total, error=repr(exc)[:200])
            raise RetryBudgetExhausted(
                f"retry budget ({self.total} per pass) exhausted; last "
                f"transient error: {exc!r}") from exc


def call_with_retry(fn: Callable, *, policy: RetryPolicy,
                    budget: RetryBudget | None = None, key: object = ""):
    """Run ``fn()`` retrying transient failures under ``policy``.

    A standalone call (no shared ``budget``) gets a private budget of
    ``policy.max_retries`` — the reader-level entry used by
    ``read_csv(retry=)`` / ``read_parquet(retry=)``.
    """
    if budget is None:
        budget = RetryBudget(policy.max_retries)
    for attempt in range(policy.max_retries + 1):
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — classified right below
            if attempt >= policy.max_retries or not policy.is_transient(e):
                raise
            budget.spend(e)
            delay = policy.delay(attempt, key)
            _emit("retry", key=str(key), attempt=attempt, delay_s=delay,
                  error=repr(e)[:200])
            policy.sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover


def retrying_source(chunks: Callable, policy: RetryPolicy) -> Callable:
    """Wrap a chunk-source factory so every pass absorbs transient failures.

    Three failure points are covered, all under ONE per-pass budget:

      * opening the source (``chunks()`` raising),
      * the iterator raising mid-pass (``next``) — a generator cannot be
        resumed after it raises, so the pass re-opens the source and
        fast-forwards past the ``k`` chunks already delivered (thunks are
        skipped unmaterialized: the fast-forward costs nothing for lazy
        sources like the from-CSV byte-range parse),
      * thunk materialization — lazy chunks stay lazy: the yielded thunk
        retries IN PLACE when called, so the device cache's skip-path
        economics are untouched.

    Chunk identity under retry is the source's own re-iteration contract
    (the same one the device cache's cached-prefix skip enforces via
    ``_fingerprint``): a retried pass must yield the same chunks in the
    same order.

    A source exposing the sharded fast-path surface (``subset()`` /
    ``with_workers()`` / ``__len__`` — :class:`~sparkglm_tpu.data.ingest.
    ShardedSource`) comes back as a :class:`RetryingSource` that FORWARDS
    that surface: narrowing/rebinding produce retry-wrapped sources again,
    so the elastic scheduler's ``subset`` sharding, ``ingest_workers=``
    rebinding, and the process-parallel checkpoint probe keep their fast
    paths under retry instead of silently degrading to full scan-and-skip.
    """
    if (hasattr(chunks, "subset") and hasattr(chunks, "with_workers")
            and hasattr(chunks, "__len__")):
        return RetryingSource(chunks, policy)
    return _retry_gen(chunks, policy)


class RetryingSource:
    """A retry-wrapped sharded chunk source: calling it streams one pass
    under the policy's budget (see :func:`retrying_source`), while the
    sharded-source narrowing surface passes through — each forwarded call
    re-wraps its result, so retry survives ``subset``/``with_workers``
    chains (the wrapper previously erased them)."""

    def __init__(self, inner, policy: RetryPolicy):
        self.inner = inner
        self.policy = policy
        self._gen = _retry_gen(inner, policy)

    def __call__(self):
        return self._gen()

    def __len__(self):
        return len(self.inner)

    @property
    def process_parallel(self) -> bool:
        return bool(getattr(self.inner, "process_parallel", False))

    def subset(self, positions) -> "RetryingSource":
        return RetryingSource(self.inner.subset(positions), self.policy)

    def with_workers(self, workers: int) -> "RetryingSource":
        return RetryingSource(self.inner.with_workers(workers), self.policy)


def _retry_gen(chunks: Callable, policy: RetryPolicy) -> Callable:
    """The per-pass retry generator factory behind :func:`retrying_source`."""

    def gen():
        budget = policy.new_budget()

        def reopen():
            for attempt in range(policy.max_retries + 1):
                try:
                    return iter(chunks())
                except BaseException as e:  # noqa: BLE001
                    if (attempt >= policy.max_retries
                            or not policy.is_transient(e)):
                        raise
                    budget.spend(e)
                    delay = policy.delay(attempt, "open")
                    _emit("retry", key="open", attempt=attempt,
                          delay_s=delay, error=repr(e)[:200])
                    policy.sleep(delay)
            raise AssertionError("unreachable")  # pragma: no cover

        it = reopen()
        k = 0  # chunks already delivered this pass
        while True:
            try:
                raw = next(it)
            except StopIteration:
                return
            except BaseException as e:  # noqa: BLE001
                if not policy.is_transient(e):
                    raise
                budget.spend(e)
                delay = policy.delay(0, ("iter", k))
                # the reopen fast-forwards past the k chunks already
                # delivered this pass; record that skip — it used to be
                # silent, hiding how much of the pass was replayed
                _emit("retry", key=f"iter:{k}", attempt=0, delay_s=delay,
                      skipped=k, error=repr(e)[:200])
                policy.sleep(delay)
                it = reopen()
                for _ in range(k):  # skip the already-delivered prefix
                    next(it)
                continue
            if callable(raw):
                def lazy(thunk=raw, idx=k):
                    return call_with_retry(thunk, policy=policy,
                                           budget=budget, key=("chunk", idx))
                yield lazy
            else:
                yield raw
            k += 1

    return gen


__all__ = [
    "TransientSourceError", "FatalSourceError", "Overloaded",
    "DeadlineExceeded", "ReplicaUnavailable", "RetryBudgetExhausted",
    "RetryPolicy", "RetryBudget", "RetryingSource", "call_with_retry",
    "retrying_source",
]
