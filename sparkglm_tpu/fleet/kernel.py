"""The fleet IRLS kernel: one executable for a whole stack of models.

``_irls_fleet_kernel`` maps the SOLO IRLS core (models/glm._irls_core — the
exact per-model computation graph every resident ``glm_fit`` compiles) over
a leading model axis.  Two batch modes, both ONE executable per (shape,
static-arg) flavor:

  * ``batch="exact"`` (default) — ``lax.map`` over the model axis: each
    model runs the UNBATCHED solo graph to its own convergence inside one
    compiled scan.  Early-converged models are fully inert (their
    while_loop simply stops — zero flops afterwards), and every model's
    coefficients / covariance / eta are bit-identical to a solo
    ``_irls_kernel`` call on the same (padded) row layout at any dtype.
    Cross-model parallelism is sacrificed; dispatch and compilation are
    amortized (the fleet win at thousands-of-small-models scale).

  * ``batch="vmap"`` — ``jax.vmap`` over the model axis: every iteration
    runs BATCHED Gramians/solves across all still-active models.  JAX's
    while_loop batching rule applies the per-model convergence predicate as
    an update MASK (``select(pred, new, old)``), so early-converged models
    go inert bit-stably: their carried state freezes the iteration they
    converge.  Iteration counts match solo fits exactly; coefficients agree
    to roundoff (~1e-15 at f64) rather than bitwise, because a batched
    GEMM's reduction order differs from the unbatched one.  This is the
    throughput mode for batched hardware (MXU-friendly (K,n,p) einsums).

Two Gramian engines per member (PR 20): ``engine="einsum"`` maps
``_irls_core`` (the exact engine), ``engine="sketch"`` maps
``_irls_sketch_core`` — the r13 sketch-and-precondition path for WIDE
per-tenant designs — with one SHARED base key, so each member's
per-iteration sketch sequence is exactly the solo ``engine="sketch"``
fit's at the same seed.

``_mesh_fleet_call`` shards the MODEL axis of the same map over a device
mesh via ``shard_map`` (parallel/mesh.py): each device runs the identical
per-member graph on its contiguous member block, so K=thousands fits in
one pass with zero cross-device collectives (members are independent).
The compiled callable is cached per (mesh, static-flavor) so warm refits
at a fixed bucket compile nothing, preserving the fleet compile contract.

Padding contracts (data/groups.py): trash ROWS carry weight 0 — inert in
every sum via the core's ``_sanitize``/valid masking; trash MODELS (fleet
bucket padding) carry all-zero weights — their first Gramian is singular
(exact engine) or their residual is identically zero (sketch engine), the
loop exits after one iteration, and the driver slices them off.  Under
the mesh both stay SHARD-LOCAL-inert: a device whose block is all trash
finishes its map immediately.
"""

from __future__ import annotations

from functools import partial

import jax

from ..models.glm import _irls_core, _irls_sketch_core

BATCH_MODES = ("exact", "vmap")
FLEET_ENGINES = ("einsum", "sketch")


def _fleet_map(X, y, wt, offset, tol, max_iter, jitter, *,
               family, link, criterion, refine_steps, precision, batch,
               fam_param, beta0, warm, engine, sketch_key, m,
               sketch_refine, sketch_method):
    """The shared member map: solo core per member under lax.map/vmap.
    Called from the jitted single-device kernel AND from inside each
    shard of the mesh kernel (where it sees only the local member
    block)."""
    def one(Xk, yk, wk, ok, bk=None):
        if engine == "sketch":
            return _irls_sketch_core(
                Xk, yk, wk, ok, sketch_key, tol, max_iter, jitter,
                family=family, link=link, criterion=criterion, m=m,
                sketch_refine=sketch_refine, sketch_method=sketch_method,
                trace=False, precision=precision, beta0=bk, warm=warm,
                fam_param=fam_param)
        return _irls_core(
            Xk, yk, wk, ok, tol, max_iter, jitter,
            family=family, link=link, criterion=criterion,
            refine_steps=refine_steps, trace=False, precision=precision,
            solver="chol", mesh=None, beta0=bk, warm=warm,
            fam_param=fam_param)

    ops = (X, y, wt, offset) + ((beta0,) if warm else ())
    if batch == "vmap":
        return jax.vmap(one)(*ops)
    return jax.lax.map(lambda o: one(*o), ops)


@partial(jax.jit, static_argnames=("family", "link", "criterion",
                                   "refine_steps", "precision", "batch",
                                   "warm", "engine", "m", "sketch_refine",
                                   "sketch_method"))
def _irls_fleet_kernel(
    X, y, wt, offset,
    tol, max_iter, jitter,
    family, link,
    criterion: str = "relative",
    refine_steps: int = 1,
    precision=None,
    batch: str = "exact",
    fam_param=None,
    beta0=None,
    warm: bool = False,
    engine: str = "einsum",
    sketch_key=None,
    m: int = 64,
    sketch_refine: int = 8,
    sketch_method: str = "countsketch",
):
    """Run IRLS for a stacked fleet: X (K, n, p); y/wt/offset (K, n).

    ``warm=True`` starts every member from its row of ``beta0`` (K, p)
    instead of the family init — the online refresh path
    (sparkglm_tpu/online): a warm fleet refit at a fixed bucket shares one
    executable with every later refresh.  Trash models (all-zero weights)
    pass a zero beta0 row and stay inert exactly as in the cold path.

    ``engine="sketch"`` maps the sketched solo core instead; the base
    ``sketch_key`` is SHARED across members (each member folds in its own
    iteration counter exactly as the solo kernel does), so member k's fit
    is the solo sketched fit of the same layout and seed.

    Returns the solo kernel's output dict with a leading (K,) axis on every
    leaf (beta (K, p), cov_inv (K, p, p), dev/iters/converged/singular/
    pivot (K,), eta (K, n), XtWX0 (K, p, p)).
    """
    return _fleet_map(
        X, y, wt, offset, tol, max_iter, jitter,
        family=family, link=link, criterion=criterion,
        refine_steps=refine_steps, precision=precision, batch=batch,
        fam_param=fam_param, beta0=beta0, warm=warm, engine=engine,
        sketch_key=sketch_key, m=m, sketch_refine=sketch_refine,
        sketch_method=sketch_method)


_MESH_CALLS: dict = {}


def _mesh_fleet_call(mesh, family, link, criterion, refine_steps,
                     precision, batch, warm, engine, m, sketch_refine,
                     sketch_method, has_fam_param):
    """Compiled member-sharded fleet kernel for ``mesh`` — the fleet's
    scale axis (b) of PR 20.

    The member (bucket) axis shards over the mesh's ``"data"`` axis; every
    other operand replicates.  Inside each shard the body is
    :func:`_fleet_map` on the LOCAL member block — the per-member graph is
    the single-device kernel's exactly (members are independent, so there
    are no collectives and no batching-order change), which is what the
    mesh-vs-unsharded parity tests lean on.  The callable is cached per
    (mesh, static flavor): refits at a fixed per-shard bucket reuse the
    executable, preserving the fleet compile contract under the mesh.
    """
    key = (mesh, family, link, criterion, refine_steps, precision, batch,
           warm, engine, m, sketch_refine, sketch_method, has_fam_param)
    fn = _MESH_CALLS.get(key)
    if fn is not None:
        return fn

    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, shard_map

    mspec = P(DATA_AXIS)   # leading member axis sharded; prefix spec
    rep = P()              # covers trailing axes of every output leaf

    n_ops = 4 + (1 if warm else 0)
    in_specs = ((mspec,) * n_ops + (rep, rep, rep)
                + ((rep,) if has_fam_param else ())
                + ((rep,) if engine == "sketch" else ()))

    def local(*args):
        X, y, wt, offset = args[:4]
        i = 4
        beta0 = None
        if warm:
            beta0 = args[i]
            i += 1
        tol, max_iter, jitter = args[i:i + 3]
        i += 3
        fam_param = None
        if has_fam_param:
            fam_param = args[i]
            i += 1
        sketch_key = args[i] if engine == "sketch" else None
        return _fleet_map(
            X, y, wt, offset, tol, max_iter, jitter,
            family=family, link=link, criterion=criterion,
            refine_steps=refine_steps, precision=precision, batch=batch,
            fam_param=fam_param, beta0=beta0, warm=warm, engine=engine,
            sketch_key=sketch_key, m=m, sketch_refine=sketch_refine,
            sketch_method=sketch_method)

    fn = jax.jit(shard_map(local, mesh=mesh, in_specs=in_specs,
                           out_specs=mspec))
    _MESH_CALLS[key] = fn
    return fn


def _irls_fleet_kernel_mesh(
    X, y, wt, offset, tol, max_iter, jitter, *, mesh,
    family, link, criterion="relative", refine_steps=1, precision=None,
    batch="exact", fam_param=None, beta0=None, warm=False,
    engine="einsum", sketch_key=None, m=64, sketch_refine=8,
    sketch_method="countsketch",
):
    """Dispatch a fleet pass member-sharded over ``mesh``.  The caller
    guarantees the bucket axis is ``per_shard_bucket * n_data_shards``
    (fleet/fitting.py sizes it)."""
    fn = _mesh_fleet_call(mesh, family, link, criterion, refine_steps,
                          precision, batch, warm, engine, m, sketch_refine,
                          sketch_method, fam_param is not None)
    args = (X, y, wt, offset) + ((beta0,) if warm else ())
    args = args + (tol, max_iter, jitter)
    if fam_param is not None:
        args = args + (fam_param,)
    if engine == "sketch":
        args = args + (sketch_key,)
    return fn(*args)


def fleet_kernel_cache_size() -> int:
    """Compiled-executable count for the fleet kernel — the contract-test
    and bench probe (one executable per pass flavor; warm refits at any
    K <= bucket add nothing).  Counts the single-device kernel AND every
    cached mesh-sharded flavor, so the mesh path rides the same
    zero-recompile contract."""
    n = int(_irls_fleet_kernel._cache_size())
    for fn in _MESH_CALLS.values():
        n += int(fn._cache_size())
    return n
