"""The fleet IRLS kernel: one executable for a whole stack of models.

``_irls_fleet_kernel`` maps the SOLO IRLS core (models/glm._irls_core — the
exact per-model computation graph every resident ``glm_fit`` compiles) over
a leading model axis.  Two batch modes, both ONE executable per (shape,
static-arg) flavor:

  * ``batch="exact"`` (default) — ``lax.map`` over the model axis: each
    model runs the UNBATCHED solo graph to its own convergence inside one
    compiled scan.  Early-converged models are fully inert (their
    while_loop simply stops — zero flops afterwards), and every model's
    coefficients / covariance / eta are bit-identical to a solo
    ``_irls_kernel`` call on the same (padded) row layout at any dtype.
    Cross-model parallelism is sacrificed; dispatch and compilation are
    amortized (the fleet win at thousands-of-small-models scale).

  * ``batch="vmap"`` — ``jax.vmap`` over the model axis: every iteration
    runs BATCHED Gramians/solves across all still-active models.  JAX's
    while_loop batching rule applies the per-model convergence predicate as
    an update MASK (``select(pred, new, old)``), so early-converged models
    go inert bit-stably: their carried state freezes the iteration they
    converge.  Iteration counts match solo fits exactly; coefficients agree
    to roundoff (~1e-15 at f64) rather than bitwise, because a batched
    GEMM's reduction order differs from the unbatched one.  This is the
    throughput mode for batched hardware (MXU-friendly (K,n,p) einsums).

Padding contracts (data/groups.py): trash ROWS carry weight 0 — inert in
every sum via the core's ``_sanitize``/valid masking; trash MODELS (fleet
bucket padding) carry all-zero weights — their first Gramian is singular,
the loop exits after one iteration, and the driver slices them off.
"""

from __future__ import annotations

from functools import partial

import jax

from ..models.glm import _irls_core

BATCH_MODES = ("exact", "vmap")


@partial(jax.jit, static_argnames=("family", "link", "criterion",
                                   "refine_steps", "precision", "batch",
                                   "warm"))
def _irls_fleet_kernel(
    X, y, wt, offset,
    tol, max_iter, jitter,
    family, link,
    criterion: str = "relative",
    refine_steps: int = 1,
    precision=None,
    batch: str = "exact",
    fam_param=None,
    beta0=None,
    warm: bool = False,
):
    """Run IRLS for a stacked fleet: X (K, n, p); y/wt/offset (K, n).

    ``warm=True`` starts every member from its row of ``beta0`` (K, p)
    instead of the family init — the online refresh path
    (sparkglm_tpu/online): a warm fleet refit at a fixed bucket shares one
    executable with every later refresh.  Trash models (all-zero weights)
    pass a zero beta0 row and stay inert exactly as in the cold path.

    Returns the solo kernel's output dict with a leading (K,) axis on every
    leaf (beta (K, p), cov_inv (K, p, p), dev/iters/converged/singular/
    pivot (K,), eta (K, n), XtWX0 (K, p, p)).
    """
    def one(Xk, yk, wk, ok, bk=None):
        return _irls_core(
            Xk, yk, wk, ok, tol, max_iter, jitter,
            family=family, link=link, criterion=criterion,
            refine_steps=refine_steps, trace=False, precision=precision,
            solver="chol", mesh=None, beta0=bk, warm=warm,
            fam_param=fam_param)

    ops = (X, y, wt, offset) + ((beta0,) if warm else ())
    if batch == "vmap":
        return jax.vmap(one)(*ops)
    return jax.lax.map(lambda o: one(*o), ops)


def fleet_kernel_cache_size() -> int:
    """Compiled-executable count for the fleet kernel — the contract-test
    and bench probe (one executable per pass flavor; warm refits at any
    K <= bucket add nothing)."""
    return int(_irls_fleet_kernel._cache_size())
