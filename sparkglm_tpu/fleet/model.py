"""FleetModel — stacked per-segment GLMs with solo-model indexing.

One fleet fit produces K models that share a design layout (same columns,
same family/link/tol) but have their own rows, coefficients, covariance and
convergence record.  The container keeps everything STACKED (leading (K,)
axis) so serving can gather coefficient rows in one batched dispatch
(serve.FamilyScorer), while ``fleet[k]`` / ``fleet["label"]`` materializes
an ordinary :class:`~sparkglm_tpu.models.glm.GLMModel` whose every field —
and therefore whose serialization — matches what a solo ``glm_fit`` of the
same (padded) row layout on a single-device mesh produces.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..models.glm import GLMModel


@dataclasses.dataclass(frozen=True)
class FleetModel:
    """K stacked GLMs fitted in one fleet kernel call."""

    # stacked per-model results (leading axis K)
    coefficients: np.ndarray        # (K, p) float64
    std_errors: np.ndarray          # (K, p) float64
    cov_unscaled: np.ndarray        # (K, p, p) float64
    deviance: np.ndarray            # (K,) float64
    null_deviance: np.ndarray       # (K,)
    pearson_chi2: np.ndarray        # (K,)
    loglik: np.ndarray              # (K,)
    aic: np.ndarray                 # (K,)
    dispersion: np.ndarray          # (K,)
    df_residual: np.ndarray         # (K,) int64
    df_null: np.ndarray             # (K,) int64
    iterations: np.ndarray          # (K,) int64
    converged: np.ndarray           # (K,) bool
    singular: np.ndarray            # (K,) bool
    n_ok: np.ndarray                # (K,) int64 — R's weights>0 row count
    has_offset: np.ndarray          # (K,) bool — per-model nonzero offset
    # shared metadata
    group_names: tuple              # K labels, aligned with the model axis
    group_name: str                 # the key column / axis name
    xnames: tuple
    yname: str
    family: str
    link: str
    n_obs: int                      # padded per-model row count (layout rows)
    n_params: int
    tol: float
    criterion: str
    has_intercept: bool
    dispersion_fixed: bool
    batch: str                      # "exact" | "vmap"
    bucket: int                     # padded power-of-2 fleet size
    formula: str | None = None
    terms: object | None = None
    fit_info: dict | None = None
    # per-member Gramian engine (PR 20): "einsum" (exact) | "sketch"
    engine: str = "einsum"
    sketch_dim: int | None = None   # engine="sketch" only
    sketch_refine: int | None = None
    # member-axis shard count the fleet pass ran with (mesh=); results are
    # gathered to host at fit time, so indexing/serialization never sees
    # the sharding — members stay byte-identical to an unsharded fit
    n_member_shards: int = 1

    @property
    def n_models(self) -> int:
        return len(self.group_names)

    def __len__(self) -> int:
        return self.n_models

    def index_of(self, key) -> int:
        """Model index for a group label (or pass an int through)."""
        if isinstance(key, (int, np.integer)):
            k = int(key)
            if not -self.n_models <= k < self.n_models:
                raise IndexError(
                    f"model index {k} out of range for fleet of "
                    f"{self.n_models}")
            return k % self.n_models
        try:
            return self.group_names.index(key)
        except ValueError:
            raise KeyError(
                f"{key!r} is not a fleet group (first few: "
                f"{list(self.group_names[:5])!r})") from None

    def __getitem__(self, key) -> GLMModel:
        k = self.index_of(key)
        sketch = self.engine == "sketch"
        # sketch members mirror the solo sketched model: no exact
        # covariance exists (models/glm.py), so cov_unscaled is None and
        # vcov() raises instead of scaling a biased sketched inverse
        cov_k = (None if sketch
                 else np.asarray(self.cov_unscaled[k], np.float64))
        return GLMModel(
            coefficients=np.asarray(self.coefficients[k], np.float64),
            std_errors=np.asarray(self.std_errors[k], np.float64),
            xnames=tuple(self.xnames), yname=self.yname,
            family=self.family, link=self.link,
            deviance=float(self.deviance[k]),
            null_deviance=float(self.null_deviance[k]),
            pearson_chi2=float(self.pearson_chi2[k]),
            loglik=float(self.loglik[k]), aic=float(self.aic[k]),
            dispersion=float(self.dispersion[k]),
            df_residual=int(self.df_residual[k]),
            df_null=int(self.df_null[k]),
            iterations=int(self.iterations[k]),
            converged=bool(self.converged[k]),
            n_obs=int(self.n_obs), n_params=int(self.n_params),
            n_shards=1, tol=float(self.tol),
            has_intercept=bool(self.has_intercept),
            cov_unscaled=cov_k,
            has_offset=bool(self.has_offset[k]),
            dispersion_fixed=bool(self.dispersion_fixed),
            gramian_engine=self.engine,
            sketch_dim=self.sketch_dim if sketch else None,
            sketch_refine=self.sketch_refine if sketch else None)

    def models(self):
        """Iterate ``(label, GLMModel)`` over the fleet."""
        for k, name in enumerate(self.group_names):
            yield name, self[k]

    def predict(self, X, group, *, offset=None, type: str = "link"):
        """Score rows against ONE fleet member's coefficients (host numpy).

        The batched serving path — many (tenant, x) requests in one
        dispatch — is :class:`sparkglm_tpu.serve.FamilyScorer`.
        """
        k = self.index_of(group)
        X = np.asarray(X, np.float64)
        eta = X @ np.asarray(self.coefficients[k], np.float64)
        if offset is not None:
            eta = eta + np.asarray(offset, np.float64)
        if type == "link":
            return eta
        if type == "response":
            from ..models import hoststats
            return hoststats.link_inverse(self.link, eta)
        raise ValueError(f"type must be 'link' or 'response', got {type!r}")

    def fit_report(self) -> dict:
        """The fleet fit's observability aggregate (obs/trace.py report),
        including the ``fleet`` block: executables compiled, per-iteration
        inert-model fraction, convergence census."""
        return self.fit_info or {}

    def summary(self) -> str:
        """Compact per-model census — one line per fleet member."""
        lines = [
            f"Model fleet: {self.n_models} x {self.family}({self.link}) "
            f"[{self.yname} ~ {len(self.xnames)} cols, "
            f"bucket={self.bucket}, batch={self.batch}]",
            f"{self.group_name:>16}  n_ok  iters  conv  deviance        aic",
        ]
        for k, name in enumerate(self.group_names):
            flag = ("yes" if self.converged[k]
                    else "SING" if self.singular[k] else "NO")
            lines.append(
                f"{str(name):>16}  {int(self.n_ok[k]):4d}  "
                f"{int(self.iterations[k]):5d}  {flag:>4}  "
                f"{float(self.deviance[k]):<14.6g}  "
                f"{float(self.aic[k]):<10.6g}")
        return "\n".join(lines)

    def save(self, path) -> None:
        from ..models.serialize import save_model
        save_model(self, path)
