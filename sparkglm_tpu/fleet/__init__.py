"""Fleet fitting: the model axis as a first-class, compiled dimension.

GLM practice at "millions of users" scale means thousands of small
per-segment models (one per region / cohort / SKU / tenant), not one giant
fit.  The reference sparkGLM fits one model per driver call; this
subsystem amortizes compilation and dispatch across the whole fleet — one
executable fits every model (ROADMAP item 3).

    import sparkglm_tpu as sg
    fleet = sg.fit_many(y, X, groups=region, family="binomial")
    fleet["emea"].summary()          # an ordinary GLMModel
    fam = sg.ModelFamily.from_fleet(fleet, name="churn")
    scorer = fam.scorer()            # batched (tenant, x) serving

Entry points: :func:`fit_many` (long-format + group key),
:func:`glm_fit_fleet` (pre-stacked (K, n, p) arrays),
:class:`FleetModel` (stacked results, indexable to GLMModels),
``data/groups.stack_groups`` (the ingestion helper).
"""

from ..data.groups import MIN_BUCKET, next_bucket, stack_groups
from .fitting import fit_many, glm_fit_fleet
from .kernel import fleet_kernel_cache_size
from .model import FleetModel
from .path import (FleetPathModel, fleet_path_kernel_cache_size,
                   glm_fit_fleet_path)

__all__ = [
    "fit_many", "glm_fit_fleet", "FleetModel", "stack_groups",
    "next_bucket", "MIN_BUCKET", "fleet_kernel_cache_size",
    "FleetPathModel", "glm_fit_fleet_path", "fleet_path_kernel_cache_size",
]
