"""Fleet fitting drivers: ``glm_fit_fleet`` (stacked arrays) and
``fit_many`` (long-format + group key).

The model axis is first-class here: one compiled fleet kernel call fits
every model (ROADMAP item 3 — thousands of per-segment models, not one
giant fit), then the reported statistics are assembled per model on the
host in float64 exactly as the solo resident path does (models/glm.py
``_fit_dispatch`` tail), so ``fleet[k]`` reproduces a solo
``glm_fit(..., mesh=single_device_mesh())`` of the same padded row layout
field-for-field — bit-identical at float64 with ``batch="exact"``.

Padding is two-axis: ragged groups pad ROWS with weight-0 trash rows
(data/groups.stack_groups), and the fleet itself pads MODELS to a
power-of-2 bucket with all-weight-0 trash models, so a warm refit of any
K <= bucket compiles nothing.
"""

from __future__ import annotations

import dataclasses
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from ..config import (DEFAULT, NumericConfig, effective_tol, x64_enabled,
                      resolve_matmul_precision)
from ..data.groups import MIN_BUCKET, next_bucket, stack_groups
from ..families.families import resolve
from ..obs import trace as _obs_trace
from .kernel import (BATCH_MODES, FLEET_ENGINES, _irls_fleet_kernel,
                     _irls_fleet_kernel_mesh, fleet_kernel_cache_size)
from .model import FleetModel


def fit_many(y, X, groups=None, *, weights=None, offset=None,
             n_rows: int | None = None, sort: bool = True,
             group_name: str = "group", **kw):
    """Fit one GLM per group in a single compiled fleet pass.

    Long-format entry: ``y`` (n,), ``X`` (n, p) — a SHARED design layout
    built once on the long frame — and ``groups`` (n,) the model key per
    row.  Rows are split by key, stacked, ragged groups padded with
    weight-0 trash rows, and the whole fleet fitted by
    :func:`glm_fit_fleet` (all of whose keywords pass through).

    Already-stacked callers (``X`` of shape (K, n, p)) may omit ``groups``;
    the call is then :func:`glm_fit_fleet` verbatim.
    """
    if groups is None:
        if np.ndim(X) != 3:
            raise ValueError(
                "fit_many needs groups= for long-format data, or an "
                "already-stacked (K, n, p) design")
        return glm_fit_fleet(X, y, weights=weights, offset=offset,
                             group_name=group_name, **kw)
    labels, Xs, ys, ws, offs, n_real = stack_groups(
        groups, X, y, weights=weights, offset=offset,
        n_rows=n_rows, sort=sort)
    return glm_fit_fleet(
        Xs, ys, weights=ws, offset=offs if offset is not None else None,
        labels=labels, group_name=group_name, **kw)


def glm_fit_fleet(
    X, y, *,
    family="binomial",
    link=None,
    weights=None,
    offset=None,
    m=None,
    tol: float = 1e-8,
    max_iter: int = 100,
    criterion: str = "relative",
    xnames=None,
    yname: str = "y",
    has_intercept: bool | None = None,
    labels=None,
    group_name: str = "group",
    batch: str = "exact",
    bucket: int | None = None,
    min_bucket: int = MIN_BUCKET,
    start=None,
    engine: str = "auto",
    penalty=None,
    mesh=None,
    verbose: bool = False,
    trace=None,
    metrics=None,
    config: NumericConfig = DEFAULT,
):
    """Fit K stacked GLMs — X (K, n, p); y/weights/offset/m (K, n).

    All models share the design layout, family/link and convergence
    policy; each has its own rows, weights, offset and convergence fate.
    ``batch="exact"`` (default) maps the solo IRLS graph per model —
    bit-identical to solo fits of the same row layout at f64;
    ``batch="vmap"`` batches iterations across models with masked updates
    (roundoff-level agreement, throughput mode).  See fleet/kernel.py.

    ``start`` (R's ``start=``) warm-starts every member from a stacked
    (K, p) coefficient init — the online refresh path
    (``sparkglm_tpu/online``): a warm refit at a fixed ``bucket`` reuses
    the warm executable, so steady-state refresh compiles nothing.  Warm
    and cold fits share the same fixed point (the IRLS map's attractor);
    only the iteration count differs.

    Singular members (rank-deficient weighted Gramian) do not raise as a
    solo fit would: they come back with NaN coefficients, converged=False
    and ``fleet.singular[k]`` set — refit offenders solo with
    ``singular='drop'`` for R-style aliasing.

    Three orthogonal axes over the same carry pytree (PR 20):
    ``engine="sketch"`` maps the r13 sketched solo core per member (wide
    per-tenant designs — same seed as the solo fit, NaN standard errors);
    ``mesh=`` shards the MODEL axis over the mesh's data axis via
    shard_map (power-of-2 member buckets per shard, trash models
    shard-local-inert, results gathered to host so indexing and
    serialization never change); ``penalty=ElasticNet(...)`` routes to
    the batched lambda-path driver (fleet/path.py) and returns a
    :class:`~sparkglm_tpu.fleet.path.FleetPathModel` instead.
    """
    from ..capabilities import check_fleet
    check_fleet(engine=engine, penalty=penalty, mesh=mesh, start=start)
    if engine == "auto":
        engine = "einsum"
    if engine not in FLEET_ENGINES:
        raise ValueError(
            f"engine must be one of {FLEET_ENGINES}, got {engine!r}")
    if penalty is not None:
        from .path import glm_fit_fleet_path
        return glm_fit_fleet_path(
            X, y, penalty=penalty, family=family, link=link,
            weights=weights, offset=offset, m=m, xnames=xnames,
            yname=yname, has_intercept=has_intercept, labels=labels,
            group_name=group_name, batch=batch, bucket=bucket,
            min_bucket=min_bucket, verbose=verbose, trace=trace,
            metrics=metrics, config=config)
    if criterion not in ("absolute", "relative"):
        raise ValueError(
            f"criterion must be 'absolute' or 'relative', got {criterion!r}")
    if batch not in BATCH_MODES:
        raise ValueError(
            f"batch must be one of {BATCH_MODES}, got {batch!r}")
    fam, lnk = resolve(family, link)
    tracer = _obs_trace.as_tracer(trace, verbose=verbose, metrics=metrics)

    X = np.asarray(X)
    y = np.asarray(y)
    if X.ndim != 3:
        raise ValueError(
            f"fleet design must be stacked (K, n, p), got shape {X.shape} — "
            "use fit_many(y, X, groups=...) to stack a long-format frame")
    K, n, p = X.shape
    if y.shape != (K, n):
        raise ValueError(f"y must be (K, n) = ({K}, {n}), got {y.shape}")
    if labels is None:
        labels = tuple(range(K))
    labels = tuple(labels)
    if len(labels) != K:
        raise ValueError(f"labels must have length K={K}, got {len(labels)}")
    if xnames is None:
        xnames = tuple(f"x{i}" for i in range(p))
    xnames = tuple(xnames)

    def _check2(v, what):
        v = np.asarray(v)
        if v.shape != (K, n):
            raise ValueError(f"{what} must be (K, n) = ({K}, {n}), "
                             f"got {v.shape}")
        return v

    use_f64 = X.dtype == np.float64 and x64_enabled()
    dtype = np.float64 if use_f64 else np.dtype(config.dtype)

    # pristine f64 host copies feed the reported statistics, exactly as the
    # solo path keeps them (models/glm.py _fit_dispatch)
    wt64 = (np.ones((K, n), np.float64) if weights is None
            else _check2(weights, "weights").astype(np.float64))
    y64 = y.astype(np.float64, copy=True)
    off64 = (np.zeros((K, n), np.float64) if offset is None
             else _check2(offset, "offset").astype(np.float64))
    from ..models.validate import (check_finite_design, check_finite_vector,
                                   check_response_domain)
    valid64 = wt64 > 0
    check_finite_vector("y", y64[valid64])
    check_finite_vector("weights", wt64)
    check_finite_vector("offset", off64)
    if m is not None:
        m64 = _check2(m, "m").astype(np.float64)
        check_finite_vector("m", m64)
        if fam.name not in ("binomial", "quasibinomial"):
            raise ValueError(
                "group sizes m only apply to the (quasi)binomial family")
        y64 = y64 / np.maximum(m64, 1e-30)
        wt64 = wt64 * m64
        valid64 = wt64 > 0
    check_response_domain(fam.name, y64[valid64])
    if has_intercept is None:
        from ..models.lm import _detect_intercept
        has_intercept = (_detect_intercept(X[0][valid64[0]], xnames)
                         if valid64[0].any() else False)

    on_tpu = jax.default_backend() == "tpu"
    mmp = resolve_matmul_precision(config, n, p, on_tpu)
    if mmp != config.matmul_precision:
        config = dataclasses.replace(config, matmul_precision=mmp)
    dev_dtype = jnp.float64 if use_f64 else jnp.float32
    tol_run = effective_tol(tol, criterion, dev_dtype)
    fam_param = fam.param_operand(dtype)

    # model-axis bucket: power-of-2 padding with all-weight-0 trash models
    # (their first Gramian is singular; the per-model loop exits after one
    # iteration and the results are sliced off below).  Under mesh= the
    # bucket is n_shards x a power-of-2 PER-SHARD block, so every device
    # holds an equal member slab and trash models stay shard-local-inert.
    n_shards = 1
    if mesh is not None:
        from ..parallel import mesh as meshlib
        n_shards = int(mesh.shape[meshlib.DATA_AXIS])
    if bucket is None:
        B = n_shards * next_bucket(-(-K // n_shards), min_bucket)
    else:
        B = int(bucket)
        if B % n_shards:
            raise ValueError(
                f"bucket={B} must divide evenly over the mesh's "
                f"{n_shards} data shards")
    if B < K:
        raise ValueError(f"bucket={B} is smaller than the fleet (K={K})")
    Xb = np.zeros((B, n, p), dtype)
    yb = np.zeros((B, n), dtype)
    wb = np.zeros((B, n), dtype)
    ob = np.zeros((B, n), dtype)
    Xb[:K] = X.astype(dtype, copy=False)
    yb[:K] = y64.astype(dtype)
    wb[:K] = wt64.astype(dtype)
    ob[:K] = off64.astype(dtype)

    warm = start is not None
    bb = None
    if warm:
        start = np.asarray(start, np.float64)
        if start.shape != (K, p):
            raise ValueError(
                f"start must be stacked (K, p) = ({K}, {p}) coefficients, "
                f"got {start.shape}")
        bb = np.zeros((B, p), dtype)
        bb[:K] = start.astype(dtype)

    # per-member sketch engine: one SHARED base key, so member k's sketch
    # sequence is the solo engine="sketch" fit's at the same seed
    sk_key = None
    m_run = 64
    if engine == "sketch":
        from ..ops.sketch import sketch_dim as _sketch_dim
        m_run = _sketch_dim(n, p, config.sketch_dim)
        sk_key = jax.random.PRNGKey(int(config.sketch_seed))

    if tracer is not None:
        tracer.emit("fleet_start", models=K, bucket=B, n_rows=n, p=p,
                    family=fam.name, link=lnk.name, batch=batch,
                    engine=engine, shards=n_shards)

    tol_dev = jnp.asarray(tol_run, dev_dtype)
    mi = jnp.asarray(max_iter, jnp.int32)
    jit_ = jnp.asarray(config.jitter, dtype)
    kern_kwargs = dict(
        family=fam, link=lnk, criterion=criterion,
        refine_steps=config.refine_steps,
        precision=config.matmul_precision, batch=batch,
        fam_param=fam_param, engine=engine, sketch_key=sk_key,
        m=int(m_run), sketch_refine=int(config.sketch_refine),
        sketch_method=config.sketch_method)
    n_exec0 = fleet_kernel_cache_size()
    from ..obs import timing as _obs_timing
    with _obs_timing.span("fleet_kernel", tracer, device=True) as _sp:
        if mesh is not None:
            out = _irls_fleet_kernel_mesh(
                Xb, yb, wb, ob, tol_dev, mi, jit_, mesh=mesh,
                beta0=bb, warm=warm, **kern_kwargs)
        else:
            out = _irls_fleet_kernel(
                Xb, yb, wb, ob, tol_dev, mi, jit_,
                beta0=bb, warm=warm, **kern_kwargs)
        _sp.watch(out)
    out = jax.tree.map(np.asarray, out)
    executables = fleet_kernel_cache_size() - n_exec0
    if tracer is not None:
        # one priced solve per fleet pass: the BUCKET's padded shapes are
        # what the device actually computed (trash models included), so
        # the capacity observatory prices B x n x p, not K x n x p
        if executables:
            tracer.emit("compile", target="fleet_kernel",
                        seconds=_sp.seconds, gramian_engine="fleet",
                        models=B, rows=n, cols=p)
        tracer.emit("solve", target="fleet_kernel",
                    iters=int(np.asarray(out["iters"][:K]).max()) if K
                    else 0,
                    seconds=_sp.seconds, gramian_engine="fleet",
                    models=B, rows=n, cols=p)

    singular = out["singular"][:K].astype(bool)
    if singular.any():
        bad = [str(labels[k]) for k in np.flatnonzero(singular)[:5]]
        warnings.warn(
            f"{int(singular.sum())} of {K} fleet members have a singular "
            f"weighted Gramian (first few: {bad}); their coefficients are "
            "NaN — refit them solo with singular='drop' for R-style "
            "aliasing", stacklevel=2)

    # ---- per-model reported statistics: host f64 from eta over the SAME
    # padded row layout the kernel saw (array length changes the pairwise-
    # sum bracketing, so slicing to real rows would break bit-parity with a
    # solo fit of this layout — hoststats masks weight-0 rows internally)
    from ..models import hoststats
    eta64 = out["eta"][:K].astype(np.float64)
    if not np.all(np.isfinite(eta64[valid64])):
        check_finite_design(X.reshape(K * n, p)[valid64.reshape(-1)])
        raise FloatingPointError(
            "non-finite linear predictor at the solution for at least one "
            "fleet member; the fit diverged — rescale predictors or lower "
            "max_iter")

    has_off_k = (np.array([bool(np.any(off64[k] != 0)) for k in range(K)])
                 if offset is not None else np.zeros(K, bool))
    eta_null = None
    if has_intercept and has_off_k.any():
        # R semantics: with an offset the null model is an intercept-only
        # GLM honouring it — one more fleet pass on a ones design (its own
        # pass flavor: same kernel, p=1 shapes)
        ones_b = np.ones((B, n, 1), dtype)
        null_kwargs = dict(
            family=fam, link=lnk, criterion=criterion,
            refine_steps=config.refine_steps,
            precision=config.matmul_precision, batch=batch,
            fam_param=fam_param)
        # the null model always runs the exact engine, as the solo sketch
        # path does (models/glm.py: null pass via _irls_kernel)
        if mesh is not None:
            null_out = _irls_fleet_kernel_mesh(
                ones_b, yb, wb, ob, tol_dev, mi, jit_, mesh=mesh,
                **null_kwargs)
        else:
            null_out = _irls_fleet_kernel(
                ones_b, yb, wb, ob, tol_dev, mi, jit_, **null_kwargs)
        eta_null = np.asarray(null_out["eta"])[:K].astype(np.float64)

    coefs = out["beta"][:K].astype(np.float64)
    cov = out["cov_inv"][:K].astype(np.float64)
    coefs[singular] = np.nan
    cov[singular] = np.nan
    iters = out["iters"][:K].astype(np.int64)
    converged = out["converged"][:K].astype(bool)

    dev = np.zeros(K)
    pearson = np.zeros(K)
    ll = np.zeros(K)
    wt_sum = np.zeros(K)
    null_dev = np.zeros(K)
    n_ok = np.zeros(K, np.int64)
    n_boundary = 0
    for k in range(K):
        hs = hoststats.glm_stats(fam.name, lnk.name, y64[k], eta64[k],
                                 wt64[k])
        dev[k], pearson[k] = hs["dev"], hs["pearson"]
        ll[k], wt_sum[k] = hs["loglik"], hs["wt_sum"]
        n_boundary += int(hs["n_boundary"])
        n_ok[k] = int(np.sum(wt64[k] > 0))
        null_dev[k] = hoststats.null_deviance(
            fam.name, lnk.name, y64[k], wt64[k], off64[k], has_intercept,
            eta_null=(eta_null[k] if eta_null is not None and has_off_k[k]
                      else None))
    hoststats.warn_separation(n_boundary)

    df_resid = n_ok - p
    with np.errstate(invalid="ignore", divide="ignore"):
        dispersion = (np.ones(K) if fam.dispersion_fixed
                      else np.where(df_resid > 0, pearson / df_resid,
                                    np.nan))
        diag = np.einsum("kpp->kp", cov)
        std_err = np.sqrt(np.maximum(dispersion[:, None] * diag, 0.0))
    aic = np.array([
        float(fam.aic(dev[k], ll[k], float(n_ok[k]), float(p), wt_sum[k]))
        for k in range(K)])
    df_null = n_ok - (1 if has_intercept else 0)

    n_bad = int(K - converged.sum())
    if n_bad:
        warnings.warn(
            f"{n_bad} of {K} fleet members did not converge in {max_iter} "
            f"iterations (|ddev| criterion {criterion!r}, tol={tol:g}); "
            "their estimates may be unreliable — raise max_iter or loosen "
            "tol", stacklevel=2)

    fit_info = None
    if tracer is not None:
        it_max = int(iters.max()) if K else 0
        inert = [float(np.mean(iters < t)) for t in range(1, it_max + 1)]
        for k in np.flatnonzero(converged):
            tracer.emit("model_converged", model=int(k),
                        label=str(labels[k]), iters=int(iters[k]))
        tracer.emit("fleet_end", models=K, bucket=B,
                    converged=int(converged.sum()),
                    singular=int(singular.sum()),
                    executables=int(executables), iters_max=it_max,
                    inert_fraction_per_iter=inert, batch=batch)
        fit_info = tracer.report()

    return FleetModel(
        coefficients=coefs, std_errors=std_err, cov_unscaled=cov,
        deviance=dev, null_deviance=null_dev, pearson_chi2=pearson,
        loglik=ll, aic=aic, dispersion=dispersion,
        df_residual=df_resid.astype(np.int64),
        df_null=df_null.astype(np.int64), iterations=iters,
        converged=converged, singular=singular, n_ok=n_ok,
        has_offset=has_off_k, group_names=labels, group_name=group_name,
        xnames=xnames, yname=yname, family=fam.name, link=lnk.name,
        n_obs=n, n_params=p, tol=tol, criterion=criterion,
        has_intercept=bool(has_intercept),
        dispersion_fixed=bool(fam.dispersion_fixed), batch=batch,
        bucket=B, fit_info=fit_info, engine=engine,
        sketch_dim=int(m_run) if engine == "sketch" else None,
        sketch_refine=(int(config.sketch_refine) if engine == "sketch"
                       else None),
        n_member_shards=n_shards)
