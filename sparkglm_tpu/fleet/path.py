"""Penalized fleets: the elastic-net lambda path batched over the model
axis (PR 20 tentpole (a)).

``_fleet_glm_path_kernel`` maps the SOLO path core
(penalized/path._glm_path_core — the exact scan every resident
``glm(penalty=)`` compiles) over a stacked (K, n, p) model axis, exactly
as fleet/kernel.py maps ``_irls_core``; gaussian/identity members run
``_fleet_gram_path_kernel`` instead: the one-data-pass stats core feeding
the accumulated-Gramian path core, both per member inside ONE executable
(the solo pair is two).  Under ``batch="exact"`` (lax.map) each member is
the UNBATCHED solo graph, so member k's whole path — its lambda grid
included — is bit-identical to a solo ``fit_path`` of the same padded row
layout; ``batch="vmap"`` batches the scan across members for throughput
(roundoff-level agreement, same iteration counts via the masked
while_loop batching rule).

Per-member lambda grids on a shared log-schedule come for free: the core
derives each member's lambda_max from ITS null-model gradient and lays
``n_lambda`` points down to ``lambda_min_ratio`` of it, with
n_lambda/ratio shared by the whole fleet (the ElasticNet spec is fleet
metadata, like family/link).  An explicit ``penalty.lambdas`` grid is
shared verbatim.

Trash members (all-zero weights, fleet bucket padding) stay inert in both
kernels: the GLM core sees zero working weights everywhere (the Gramian,
gradient and lambda_max collapse to 0/_TINY and every inner loop exits on
its first test), the gram core's NaN moments fail every ``>`` predicate
(one CD sweep per point, no admission rounds) — no member hangs, and the
driver slices them off.

``FleetPathModel`` keeps everything stacked — ``fleet_path[k]`` is an
ordinary :class:`~sparkglm_tpu.penalized.model.PathModel`, and
``select(lambda_=|criterion=)`` collapses every member's path point into
a :class:`~sparkglm_tpu.fleet.model.FleetModel`, so serving
(serve.ModelFamily.from_fleet) and continuous learning (online.OnlineLoop)
compose with penalized fleets through the existing plumbing with zero new
code paths.
"""

from __future__ import annotations

import dataclasses
import functools
import warnings

import jax
import numpy as np

from ..config import (DEFAULT, NumericConfig, resolve_matmul_precision,
                      x64_enabled)
from ..data.groups import MIN_BUCKET, next_bucket
from ..families.families import resolve
from ..obs import trace as _obs_trace
from ..penalized.model import PathModel
from ..penalized.path import (_KKT_ROUNDS, _glm_path_core, _gram_path_core,
                              _quad_stats_core, intercept_col,
                              resolve_penalty_vector)
from ..penalized.penalty import ElasticNet
from .kernel import BATCH_MODES
from .model import FleetModel

__all__ = ["FleetPathModel", "glm_fit_fleet_path",
           "fleet_path_kernel_cache_size"]

_FLEET_GLM_STATICS = ("family", "link", "auto_grid", "n_lambda",
                      "standardize", "icol", "max_iter", "cd_max_sweeps",
                      "kkt_rounds", "precision", "batch")


@functools.partial(jax.jit, static_argnames=_FLEET_GLM_STATICS)
def _fleet_glm_path_kernel(X, y, wt, off, lambdas, lmr, alpha, pf, tol,
                           cd_tol, fam_param, *, family, link, auto_grid,
                           n_lambda, standardize, icol, max_iter,
                           cd_max_sweeps, kkt_rounds, precision, batch):
    """K whole lambda paths in one executable: X (K, n, p); y/wt/off
    (K, n); the penalty operands (grid, ratio, alpha, factors, tols) are
    SHARED — the fleet contract, as with family/link on the IRLS fleet.
    Returns the solo path dict with a leading (K,) axis on every leaf."""
    def one(Xk, yk, wk, ok):
        return _glm_path_core(
            Xk, yk, wk, ok, lambdas, lmr, alpha, pf, tol, cd_tol,
            fam_param, family=family, link=link, auto_grid=auto_grid,
            n_lambda=n_lambda, standardize=standardize, icol=icol,
            max_iter=max_iter, cd_max_sweeps=cd_max_sweeps,
            kkt_rounds=kkt_rounds, precision=precision, trace=False)

    ops = (X, y, wt, off)
    if batch == "vmap":
        return jax.vmap(one)(*ops)
    return jax.lax.map(lambda o: one(*o), ops)


_FLEET_GRAM_STATICS = ("auto_grid", "n_lambda", "standardize", "icol",
                       "cd_max_sweeps", "kkt_rounds", "precision", "batch")


@functools.partial(jax.jit, static_argnames=_FLEET_GRAM_STATICS)
def _fleet_gram_path_kernel(X, y, wt, off, lambdas, lmr, alpha, pf, cd_tol,
                            *, auto_grid, n_lambda, standardize, icol,
                            cd_max_sweeps, kkt_rounds, precision, batch):
    """Gaussian/identity fleet paths: per member, the one-data-pass stats
    core feeds the accumulated-Gramian path core — the solo TWO-executable
    pair fused into one fleet executable (the quadratic objective never
    re-weights, so after the stats pass everything is p x p work)."""
    def one(Xk, yk, wk, ok):
        st = _quad_stats_core(Xk, yk, wk, ok, precision=precision)
        return _gram_path_core(
            st["A"], st["b"], st["s1"], st["yty"], st["wsum"], lambdas,
            lmr, alpha, pf, cd_tol, auto_grid=auto_grid,
            n_lambda=n_lambda, standardize=standardize, icol=icol,
            cd_max_sweeps=cd_max_sweeps, kkt_rounds=kkt_rounds,
            trace=False)

    ops = (X, y, wt, off)
    if batch == "vmap":
        return jax.vmap(one)(*ops)
    return jax.lax.map(lambda o: one(*o), ops)


def fleet_path_kernel_cache_size() -> int:
    """Compiled-executable count across both fleet path kernels — the
    bench/contract probe (a warm refit at a fixed bucket adds zero)."""
    return (int(_fleet_glm_path_kernel._cache_size())
            + int(_fleet_gram_path_kernel._cache_size()))


@dataclasses.dataclass(frozen=True)
class FleetPathModel:
    """K stacked elastic-net lambda paths fitted in one fleet kernel call.

    ``fleet_path[k]`` / ``fleet_path["label"]`` materializes an ordinary
    :class:`PathModel` (field-for-field what a solo ``fit_path`` of the
    member's padded row layout produces under ``batch="exact"``);
    :meth:`select` collapses one path point per member into a
    :class:`FleetModel` for batched serving.
    """

    # stacked per-member path results (leading axis K)
    lambdas: np.ndarray          # (K, L) descending, per-member grids
    coefficients: np.ndarray     # (K, L, p) ORIGINAL scale
    df: np.ndarray               # (K, L) int64
    deviance: np.ndarray         # (K, L)
    dev_ratio: np.ndarray        # (K, L)
    null_deviance: np.ndarray    # (K,)
    converged: np.ndarray        # (K, L) bool, per path point
    kkt_clean: np.ndarray        # (K, L) bool
    iterations: np.ndarray       # (K, L) int64 IRLS iters per point
    sweeps: np.ndarray           # (K, L) int64 CD sweeps per point
    n_ok: np.ndarray             # (K,) int64
    has_offset: np.ndarray       # (K,) bool
    # shared metadata
    alpha: float
    group_names: tuple
    group_name: str
    xnames: tuple
    yname: str
    family: str
    link: str
    n_obs: int                   # padded per-member row count
    n_params: int
    has_intercept: bool
    standardize: bool
    penalty: object              # the shared ElasticNet spec
    dispersion_fixed: bool
    batch: str
    bucket: int
    kind: str = "glm"
    formula: str | None = None
    terms: object | None = None
    fit_info: dict | None = None

    @property
    def n_models(self) -> int:
        return len(self.group_names)

    @property
    def n_lambda(self) -> int:
        return int(self.lambdas.shape[1])

    def __len__(self) -> int:
        return self.n_models

    def index_of(self, key) -> int:
        """Model index for a group label (or pass an int through)."""
        if isinstance(key, (int, np.integer)):
            k = int(key)
            if not -self.n_models <= k < self.n_models:
                raise IndexError(
                    f"model index {k} out of range for fleet of "
                    f"{self.n_models}")
            return k % self.n_models
        try:
            return self.group_names.index(key)
        except ValueError:
            raise KeyError(
                f"{key!r} is not a fleet group (first few: "
                f"{list(self.group_names[:5])!r})") from None

    def __getitem__(self, key) -> PathModel:
        k = self.index_of(key)
        return PathModel(
            lambdas=np.asarray(self.lambdas[k], np.float64),
            alpha=float(self.alpha),
            coefficients=np.asarray(self.coefficients[k], np.float64),
            df=np.asarray(self.df[k], np.int64),
            deviance=np.asarray(self.deviance[k], np.float64),
            dev_ratio=np.asarray(self.dev_ratio[k], np.float64),
            null_deviance=float(self.null_deviance[k]),
            family=self.family, link=self.link, xnames=tuple(self.xnames),
            yname=self.yname, n_obs=int(self.n_obs), n_ok=int(self.n_ok[k]),
            n_params=int(self.n_params),
            has_intercept=bool(self.has_intercept),
            standardize=bool(self.standardize), penalty=self.penalty,
            converged=bool(self.converged[k].all()),
            kkt_clean=bool(self.kkt_clean[k].all()),
            iterations=int(self.iterations[k].sum()),
            dispersion_fixed=bool(self.dispersion_fixed), kind=self.kind,
            has_offset=bool(self.has_offset[k]),
            gramian_engine="einsum")

    def models(self):
        """Iterate ``(label, PathModel)`` over the fleet."""
        for k, name in enumerate(self.group_names):
            yield name, self[k]

    def _indices(self, lambda_=None, criterion=None) -> np.ndarray:
        """Per-member selected path-point index."""
        if (lambda_ is None) == (criterion is None):
            raise ValueError(
                "pass exactly one of lambda_= or criterion='aic'|'bic'")
        K = self.n_models
        if lambda_ is not None:
            lam = float(lambda_)
            if not np.isfinite(lam) or lam < 0:
                raise ValueError(
                    f"lambda_ must be finite and >= 0, got {lambda_!r}")
            # per-member grids: nearest point in log distance per member,
            # matching PathModel.lambda_index
            grid = np.maximum(np.asarray(self.lambdas[:K], np.float64),
                              1e-300)
            return np.argmin(np.abs(np.log(grid)
                                    - np.log(max(lam, 1e-300))), axis=1)
        if criterion not in ("aic", "bic"):
            raise ValueError(
                f"criterion must be 'aic' or 'bic', got {criterion!r}")
        ic = 1.0 if self.has_intercept else 0.0
        dev = np.asarray(self.deviance[:K], np.float64)
        dft = np.asarray(self.df[:K], np.float64) + ic
        if criterion == "aic":
            kfac = np.full(K, 2.0)
        else:
            kfac = np.log(np.maximum(self.n_ok[:K].astype(np.float64), 2.0))
        return np.argmin(dev + kfac[:, None] * dft, axis=1)

    def select(self, lambda_: float | None = None,
               criterion: str | None = None) -> FleetModel:
        """Collapse one path point per member into a :class:`FleetModel`.

        Selection semantics are :meth:`PathModel.select`'s applied per
        member (nearest grid point on the MEMBER's grid, or the member's
        own aic/bic minimizer).  The result serves and learns through
        every existing fleet surface — ``ModelFamily.from_fleet``,
        ``FamilyScorer``, ``OnlineLoop`` — with NaN standard errors (no
        post-selection inference, penalized/model.py docstring).
        """
        idx = self._indices(lambda_, criterion)
        K = self.n_models
        p = int(self.n_params)
        ar = np.arange(K)
        beta = np.asarray(self.coefficients[ar, idx], np.float64)
        dev = np.asarray(self.deviance[ar, idx], np.float64)
        df_used = (self.df[ar, idx].astype(np.int64)
                   + (1 if self.has_intercept else 0))
        df_resid = np.maximum(self.n_ok.astype(np.int64) - df_used, 0)
        df_null = self.n_ok.astype(np.int64) - (1 if self.has_intercept
                                                else 0)
        nan_v = np.full(K, np.nan)
        disp = (np.ones(K) if self.dispersion_fixed else np.full(K, np.nan))
        sel = {
            "penalized": {
                "alpha": float(self.alpha),
                "criterion": criterion,
                "lambda": [float(v) for v in self.lambdas[ar, idx]],
                "lambda_index": [int(i) for i in idx],
                "n_lambda": self.n_lambda,
                "df": [int(d) for d in self.df[ar, idx]],
                "standardize": bool(self.standardize),
            }
        }
        return FleetModel(
            coefficients=beta, std_errors=np.full((K, p), np.nan),
            cov_unscaled=np.full((K, p, p), np.nan), deviance=dev,
            null_deviance=np.asarray(self.null_deviance, np.float64),
            pearson_chi2=nan_v, loglik=nan_v.copy(), aic=nan_v.copy(),
            dispersion=disp, df_residual=df_resid, df_null=df_null,
            iterations=self.iterations.sum(axis=1).astype(np.int64),
            converged=self.converged.all(axis=1),
            singular=np.zeros(K, bool),
            n_ok=self.n_ok.astype(np.int64),
            has_offset=self.has_offset.astype(bool),
            group_names=self.group_names, group_name=self.group_name,
            xnames=tuple(self.xnames), yname=self.yname,
            family=self.family, link=self.link, n_obs=int(self.n_obs),
            n_params=p,
            tol=float(self.penalty.tol if self.penalty is not None
                      else 1e-7),
            criterion="relative", has_intercept=bool(self.has_intercept),
            dispersion_fixed=bool(self.dispersion_fixed), batch=self.batch,
            bucket=int(self.bucket), formula=self.formula,
            terms=self.terms, fit_info=sel)

    def fit_report(self) -> dict:
        return self.fit_info or {}

    def summary(self) -> str:
        """Compact per-member path census — one line per fleet member."""
        lines = [
            f"Penalized fleet: {self.n_models} x {self.family}({self.link}) "
            f"paths [alpha={self.alpha:g}, n_lambda={self.n_lambda}, "
            f"bucket={self.bucket}, batch={self.batch}]",
            f"{self.group_name:>16}  n_ok  lam_max    lam_min    df_max  "
            "dev_ratio_max",
        ]
        for k, name in enumerate(self.group_names):
            lines.append(
                f"{str(name):>16}  {int(self.n_ok[k]):4d}  "
                f"{float(self.lambdas[k, 0]):<9.4g}  "
                f"{float(self.lambdas[k, -1]):<9.4g}  "
                f"{int(self.df[k].max(initial=0)):6d}  "
                f"{float(np.max(self.dev_ratio[k], initial=0.0)):.4f}")
        return "\n".join(lines)

    def save(self, path) -> None:
        from ..models.serialize import save_model
        save_model(self, path)


def glm_fit_fleet_path(
    X, y, *,
    penalty,
    family="gaussian",
    link=None,
    weights=None,
    offset=None,
    m=None,
    xnames=None,
    yname: str = "y",
    has_intercept: bool | None = None,
    labels=None,
    group_name: str = "group",
    batch: str = "exact",
    bucket: int | None = None,
    min_bucket: int = MIN_BUCKET,
    kind: str = "glm",
    verbose: bool = False,
    trace=None,
    metrics=None,
    config: NumericConfig = DEFAULT,
) -> FleetPathModel:
    """Fit K stacked elastic-net lambda paths — X (K, n, p); y/weights/
    offset/m (K, n) — in one compiled fleet-path kernel call.

    The penalized arm of :func:`~sparkglm_tpu.fleet.glm_fit_fleet`
    (``glm_fleet(..., penalty=ElasticNet(...))`` routes here).  Validation,
    padding and bucketing mirror the IRLS fleet driver; convergence policy
    (tol/max_iter/cd tolerances) comes from the shared ElasticNet spec,
    exactly as on the solo path.
    """
    if not isinstance(penalty, ElasticNet):
        raise TypeError(
            f"penalty must be an ElasticNet instance, got {type(penalty)!r}")
    if batch not in BATCH_MODES:
        raise ValueError(
            f"batch must be one of {BATCH_MODES}, got {batch!r}")
    fam, lnk = resolve(family, link)
    tracer = _obs_trace.as_tracer(trace, verbose=verbose, metrics=metrics)

    X = np.asarray(X)
    y = np.asarray(y)
    if X.ndim != 3:
        raise ValueError(
            f"fleet design must be stacked (K, n, p), got shape {X.shape} — "
            "use fit_many(y, X, groups=...) to stack a long-format frame")
    K, n, p = X.shape
    if y.shape != (K, n):
        raise ValueError(f"y must be (K, n) = ({K}, {n}), got {y.shape}")
    if labels is None:
        labels = tuple(range(K))
    labels = tuple(labels)
    if len(labels) != K:
        raise ValueError(f"labels must have length K={K}, got {len(labels)}")
    if xnames is None:
        xnames = tuple(f"x{i}" for i in range(p))
    xnames = tuple(xnames)

    def _check2(v, what):
        v = np.asarray(v)
        if v.shape != (K, n):
            raise ValueError(f"{what} must be (K, n) = ({K}, {n}), "
                             f"got {v.shape}")
        return v

    use_f64 = X.dtype == np.float64 and x64_enabled()
    dtype = np.float64 if use_f64 else np.dtype(config.dtype)

    wt64 = (np.ones((K, n), np.float64) if weights is None
            else _check2(weights, "weights").astype(np.float64))
    y64 = y.astype(np.float64, copy=True)
    off64 = (np.zeros((K, n), np.float64) if offset is None
             else _check2(offset, "offset").astype(np.float64))
    from ..models.validate import check_finite_vector, check_response_domain
    valid64 = wt64 > 0
    check_finite_vector("y", y64[valid64])
    check_finite_vector("weights", wt64)
    check_finite_vector("offset", off64)
    if m is not None:
        m64 = _check2(m, "m").astype(np.float64)
        check_finite_vector("m", m64)
        if fam.name not in ("binomial", "quasibinomial"):
            raise ValueError(
                "group sizes m only apply to the (quasi)binomial family")
        y64 = y64 / np.maximum(m64, 1e-30)
        wt64 = wt64 * m64
        valid64 = wt64 > 0
    check_response_domain(fam.name, y64[valid64])
    per_wsum = wt64.sum(axis=1)
    if (per_wsum <= 0.0).any():
        bad = [str(labels[k]) for k in np.flatnonzero(per_wsum <= 0.0)[:5]]
        raise ValueError(
            f"fleet members with zero total weight cannot fit a lambda "
            f"path (first few: {bad}) — drop them before stacking")
    if has_intercept is None:
        from ..models.lm import _detect_intercept
        has_intercept = (_detect_intercept(X[0][valid64[0]], xnames)
                         if valid64[0].any() else False)
    icol = intercept_col(list(xnames), has_intercept)

    pfv = resolve_penalty_vector(penalty, list(xnames), has_intercept, icol)
    explicit = penalty.resolved_lambdas()
    auto_grid = explicit is None
    n_lambda = penalty.grid_size()
    lmr = penalty.min_ratio(n, p - (1 if icol is not None else 0))

    on_tpu = jax.default_backend() == "tpu"
    mmp = resolve_matmul_precision(config, n, p, on_tpu)

    # model-axis bucket, as on the IRLS fleet: power-of-2 padding with
    # all-weight-0 trash models (inert in both path cores — module
    # docstring) sliced off below
    B = next_bucket(K, min_bucket) if bucket is None else int(bucket)
    if B < K:
        raise ValueError(f"bucket={B} is smaller than the fleet (K={K})")
    Xb = np.zeros((B, n, p), dtype)
    yb = np.zeros((B, n), dtype)
    wb = np.zeros((B, n), dtype)
    ob = np.zeros((B, n), dtype)
    Xb[:K] = X.astype(dtype, copy=False)
    yb[:K] = y64.astype(dtype)
    wb[:K] = wt64.astype(dtype)
    ob[:K] = off64.astype(dtype)

    alpha_in = np.asarray(penalty.alpha, dtype)
    pf_in = pfv.astype(dtype)
    lam_in = (np.zeros((n_lambda,), dtype) if auto_grid
              else explicit.astype(dtype))
    lmr_in = np.asarray(lmr, dtype)
    cd_tol_in = np.asarray(penalty.cd_tol, dtype)
    gaussian_identity = fam.name == "gaussian" and lnk.name == "identity"

    if tracer is not None:
        tracer.emit("fleet_path_start", models=K, bucket=B, n_rows=n, p=p,
                    family=fam.name, link=lnk.name, batch=batch,
                    alpha=float(penalty.alpha), n_lambda=n_lambda)

    n_exec0 = fleet_path_kernel_cache_size()
    from ..obs import timing as _obs_timing
    with _obs_timing.span("fleet_path_kernel", tracer, device=True) as _sp:
        if gaussian_identity:
            out = _fleet_gram_path_kernel(
                Xb, yb, wb, ob, lam_in, lmr_in, alpha_in, pf_in, cd_tol_in,
                auto_grid=auto_grid, n_lambda=n_lambda,
                standardize=penalty.standardize, icol=icol,
                cd_max_sweeps=penalty.cd_max_sweeps,
                kkt_rounds=_KKT_ROUNDS, precision=mmp, batch=batch)
            target = "fleet_gram_path"
        else:
            out = _fleet_glm_path_kernel(
                Xb, yb, wb, ob, lam_in, lmr_in, alpha_in, pf_in,
                np.asarray(penalty.tol, dtype), cd_tol_in,
                fam.param_operand(dtype), family=fam, link=lnk,
                auto_grid=auto_grid, n_lambda=n_lambda,
                standardize=penalty.standardize, icol=icol,
                max_iter=penalty.max_iter,
                cd_max_sweeps=penalty.cd_max_sweeps,
                kkt_rounds=_KKT_ROUNDS, precision=mmp, batch=batch)
            target = "fleet_glm_path"
        _sp.watch(out)
    out = jax.tree.map(np.asarray, out)
    executables = fleet_path_kernel_cache_size() - n_exec0
    if tracer is not None:
        if executables:
            tracer.emit("compile", target=target, seconds=_sp.seconds,
                        gramian_engine="fleet", models=B, rows=n, cols=p)
        tracer.emit("solve", target=target,
                    iters=int(out["iters"][:K].sum()) if K else 0,
                    seconds=_sp.seconds, gramian_engine="fleet",
                    models=B, rows=n, cols=p)

    lambdas = out["lambdas"][:K].astype(np.float64)
    betas = out["beta"][:K].astype(np.float64)
    dev = out["dev"][:K].astype(np.float64)
    null_dev = out["null_dev"][:K].astype(np.float64)
    df = out["df"][:K].astype(np.int64)
    conv = out["conv"][:K].astype(bool)
    kkt_ok = out["kkt_ok"][:K].astype(bool)
    iters = out["iters"][:K].astype(np.int64)
    sweeps = out["sweeps"][:K].astype(np.int64)
    with np.errstate(invalid="ignore", divide="ignore"):
        dev_ratio = np.where(null_dev[:, None] > 0,
                             1.0 - dev / null_dev[:, None], 0.0)
    n_ok = (wt64 > 0).sum(axis=1).astype(np.int64)
    has_off_k = (np.array([bool(np.any(off64[k] != 0)) for k in range(K)])
                 if offset is not None else np.zeros(K, bool))

    bad_members = int((~conv.all(axis=1)).sum())
    if bad_members:
        warnings.warn(
            f"penalized fleet: {bad_members}/{K} members have lambda "
            f"points that hit the iteration cap "
            f"(max_iter={penalty.max_iter}, "
            f"cd_max_sweeps={penalty.cd_max_sweeps}) before reaching "
            f"tol={penalty.tol:g}; estimates there may be loose",
            stacklevel=2)

    fit_info = None
    if tracer is not None:
        tracer.emit("fleet_path_end", models=K, bucket=B,
                    converged=int(conv.all(axis=1).sum()),
                    kkt_clean=int(kkt_ok.all(axis=1).sum()),
                    executables=int(executables),
                    irls_iters_total=int(iters.sum()),
                    cd_sweeps_total=int(sweeps.sum()), batch=batch)
        fit_info = tracer.report()
        fit_info["fleet_path"] = {
            "models": int(K), "bucket": int(B),
            "n_lambda": int(n_lambda), "alpha": float(penalty.alpha),
            "executables": int(executables),
            "irls_iters_total": int(iters.sum()),
            "cd_sweeps_total": int(sweeps.sum()),
        }

    return FleetPathModel(
        lambdas=lambdas, coefficients=betas, df=df, deviance=dev,
        dev_ratio=np.asarray(dev_ratio, np.float64),
        null_deviance=null_dev, converged=conv, kkt_clean=kkt_ok,
        iterations=iters, sweeps=sweeps, n_ok=n_ok, has_offset=has_off_k,
        alpha=float(penalty.alpha), group_names=labels,
        group_name=group_name, xnames=xnames, yname=yname, family=fam.name,
        link=lnk.name, n_obs=n, n_params=p,
        has_intercept=bool(has_intercept),
        standardize=bool(penalty.standardize), penalty=penalty,
        dispersion_fixed=bool(fam.dispersion_fixed), batch=batch,
        bucket=B, kind=kind, fit_info=fit_info)
