from .families import (FAMILIES, Family, binomial, gamma, gaussian,
                       get_family, inverse_gaussian, poisson, resolve)
from .links import LINKS, Link, get_link
