"""Link functions as pure jnp records.

Generalises the reference's copy-pasted per-link objects — logit
(/root/reference/src/main/scala/com/Alteryx/sparkGLM/GLM.scala:190-204),
probit (GLM.scala:207-234, which loops rowwise over Gaussian distribution
objects) and cloglog (GLM.scala:237-251) — into one ``Link`` record of three
element-wise functions that XLA fuses straight into the IRLS step.  This also
fixes the reference's 3-4x recomputation of ``unlink``/``lPrime`` per row per
iteration inside one map closure (GLM.scala:370-371): here each quantity is a
named intermediate computed once and fused.

Each link provides:
  * ``link(mu)     -> eta``    (g)
  * ``inverse(eta) -> mu``     (g^-1)
  * ``deriv(mu)    -> g'(mu)`` (dg/dmu — the IRLS working-response slope)

Saturation guards: probit/cloglog/logit inverses clamp eta (and mu away from
{0,1}) so IRLS weights ``w = 1/(Var(mu) g'(mu)^2)`` stay finite — the
reference's only guard is a ``max(y,1)`` inside the deviance
(GLM.scala:167); SURVEY.md §7 "hard parts" #5 calls out the rest.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax.numpy as jnp
from jax.scipy.special import ndtri
from jax.scipy.stats import norm

_EPS = 1e-7  # mu clamp for (0,1)-valued families
_ETA_MAX = 30.0  # |eta| clamp for exp-overflow links


@dataclasses.dataclass(frozen=True)
class Link:
    name: str
    link: Callable
    inverse: Callable
    deriv: Callable


def _clip_unit(mu):
    return jnp.clip(mu, _EPS, 1.0 - _EPS)


def _logit(mu):
    mu = _clip_unit(mu)
    return jnp.log(mu) - jnp.log1p(-mu)


def _logit_inv(eta):
    return _clip_unit(jnp.where(eta >= 0, 1.0 / (1.0 + jnp.exp(-eta)),
                                jnp.exp(eta) / (1.0 + jnp.exp(eta))))


def _probit_inv(eta):
    return _clip_unit(norm.cdf(eta))


def _probit_deriv(mu):
    # dg/dmu = 1/phi(g(mu)) — reference computes the same rowwise with
    # Gaussian objects (GLM.scala:219-224).
    return 1.0 / jnp.maximum(norm.pdf(ndtri(_clip_unit(mu))), 1e-30)


def _cloglog(mu):
    return jnp.log(-jnp.log1p(-_clip_unit(mu)))


def _cloglog_inv(eta):
    eta = jnp.clip(eta, -_ETA_MAX, _ETA_MAX)
    return _clip_unit(-jnp.expm1(-jnp.exp(eta)))


def _cloglog_deriv(mu):
    mu = _clip_unit(mu)
    return -1.0 / ((1.0 - mu) * jnp.log1p(-mu))


def _log_inv(eta):
    return jnp.exp(jnp.clip(eta, -_ETA_MAX, _ETA_MAX))


identity = Link("identity", lambda mu: mu, lambda eta: eta,
                lambda mu: jnp.ones_like(mu))
log = Link("log", lambda mu: jnp.log(jnp.maximum(mu, 1e-30)), _log_inv,
           lambda mu: 1.0 / jnp.maximum(mu, 1e-30))
logit = Link("logit", _logit, _logit_inv,
             lambda mu: 1.0 / jnp.maximum(_clip_unit(mu) * (1.0 - _clip_unit(mu)), 1e-30))
probit = Link("probit", lambda mu: ndtri(_clip_unit(mu)), _probit_inv, _probit_deriv)
cloglog = Link("cloglog", _cloglog, _cloglog_inv, _cloglog_deriv)
inverse = Link("inverse", lambda mu: 1.0 / mu, lambda eta: 1.0 / eta,
               lambda mu: -1.0 / (mu * mu))
sqrt = Link("sqrt", jnp.sqrt, lambda eta: eta * eta,
            lambda mu: 0.5 / jnp.sqrt(jnp.maximum(mu, 1e-30)))
inverse_squared = Link("inverse_squared", lambda mu: 1.0 / (mu * mu),
                       lambda eta: 1.0 / jnp.sqrt(jnp.maximum(eta, 1e-30)),
                       lambda mu: -2.0 / (mu * mu * mu))

LINKS: dict[str, Link] = {
    l.name: l for l in (identity, log, logit, probit, cloglog, inverse, sqrt,
                        inverse_squared)
}


def get_link(link: str | Link) -> Link:
    if isinstance(link, Link):
        return link
    try:
        return LINKS[link]
    except KeyError:
        raise ValueError(f"unknown link {link!r}; available: {sorted(LINKS)}") from None
