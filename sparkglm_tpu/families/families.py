"""Exponential-family records for IRLS.

The reference declares a ``family`` string but implements only binomial —
every other family's dispatch falls through to the binomial fitter
(/root/reference/src/main/scala/com/Alteryx/sparkGLM/GLM.scala:486-490,
586-590).  SURVEY.md §7 makes gaussian/poisson/gamma (plus inverse-gaussian)
mandatory; building the general ``Family`` record is *less* code than the
reference's per-link copy-paste.

Each family provides pure element-wise jnp functions (fused by XLA into the
IRLS step):
  * ``variance(mu)`` — V(mu)                 (ref: varianceBinomial GLM.scala:125-129)
  * ``dev_resids(y, mu, wt)`` — per-row deviance contributions
                                              (ref: devBinomial GLM.scala:162-170)
  * ``loglik_terms(y, mu, wt)`` — per-row exact log-likelihood
                                              (ref: llBinomial GLM.scala:132-143,
                                               which builds a Breeze Binomial
                                               object per row; here a stable
                                               gammaln form)
  * ``init_mu(y, wt)`` — IRLS starting mean  (ref: ybar*ones GLM.scala:420-424)
  * ``aic(dev, loglik, n, p, wt_sum)``        (ref: createObj GLM.scala:59-88,
                                               aic = -2 ll + 2 p)

Conventions follow R's ``glm`` (the reference's stated oracle, SURVEY.md §4):
for binomial with group sizes m, ``y`` is the *proportion* of successes and
``wt`` carries m (the reference's ``m`` argument, GLM.scala:254-315); the
top-level ``glm()`` front-end converts counts+m into this form.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax.numpy as jnp
from jax.scipy.special import gammaln

from .links import Link, get_link

_EPS = 1e-10


def _xlogy(x, y):
    """x * log(y) with 0*log(0) = 0."""
    return jnp.where(x == 0.0, 0.0, x * jnp.log(jnp.maximum(y, _EPS)))


@dataclasses.dataclass(frozen=True)
class Family:
    name: str
    variance: Callable
    dev_resids: Callable          # (y, mu, wt) -> per-row deviance
    loglik_terms: Callable        # (y, mu, wt) -> per-row log-likelihood
    init_mu: Callable             # (y, wt) -> mu0 per row
    default_link: str
    dispersion_fixed: bool        # True: dispersion == 1 (binomial, poisson)
    # aic(dev_total, loglik_total, n_obs, n_params, wt) -> scalar
    aic: Callable = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.aic is None:
            object.__setattr__(
                self, "aic",
                lambda dev, ll, n, p, wt_sum: -2.0 * ll + 2.0 * p)


# ----------------------------------------------------------------------------
# gaussian
# ----------------------------------------------------------------------------

def _gaussian_ll(y, mu, wt):
    # matches R: profile out sigma^2 at the MLE — handled at the aggregate
    # level in glm.py via the gaussian aic; per-row terms carry wt*(y-mu)^2.
    return -0.5 * wt * (y - mu) ** 2


gaussian = Family(
    name="gaussian",
    variance=lambda mu: jnp.ones_like(mu),
    dev_resids=lambda y, mu, wt: wt * (y - mu) ** 2,
    loglik_terms=_gaussian_ll,
    init_mu=lambda y, wt: y,
    default_link="identity",
    dispersion_fixed=False,
    # R: aic = n*(log(2*pi*dev/n)+1) + 2  -> plus 2*(p+1) for params+sigma
    aic=lambda dev, ll, n, p, wt_sum:
        n * (jnp.log(2.0 * jnp.pi * dev / n) + 1.0) + 2.0 * (p + 1.0),
)


# ----------------------------------------------------------------------------
# binomial  (y = proportion successes, wt = group size m * prior weight)
# ----------------------------------------------------------------------------

def _binom_dev(y, mu, wt):
    # 2*wt*[y log(y/mu) + (1-y) log((1-y)/(1-mu))], with xlogy guards — the
    # reference guards only via max(y,1) on counts (GLM.scala:167).
    return 2.0 * wt * (_xlogy(y, y) - _xlogy(y, mu)
                       + _xlogy(1.0 - y, 1.0 - y) - _xlogy(1.0 - y, 1.0 - mu))


def _binom_ll(y, mu, wt):
    # exact Binomial(m, mu) log-pmf at counts k = wt*y via gammaln
    # (ref llBinomial builds a distribution object per row, GLM.scala:132-143)
    k = wt * y
    comb = gammaln(wt + 1.0) - gammaln(k + 1.0) - gammaln(wt - k + 1.0)
    return comb + _xlogy(k, mu) + _xlogy(wt - k, 1.0 - mu)


binomial = Family(
    name="binomial",
    variance=lambda mu: mu * (1.0 - mu),
    dev_resids=_binom_dev,
    loglik_terms=_binom_ll,
    # R's binomial initialize: mustart = (wt*y + 0.5)/(wt + 1)
    init_mu=lambda y, wt: (wt * y + 0.5) / (wt + 1.0),
    default_link="logit",
    dispersion_fixed=True,
)


# ----------------------------------------------------------------------------
# poisson
# ----------------------------------------------------------------------------

def _pois_dev(y, mu, wt):
    return 2.0 * wt * (_xlogy(y, y) - _xlogy(y, mu) - (y - mu))


def _pois_ll(y, mu, wt):
    return wt * (_xlogy(y, mu) - mu - gammaln(y + 1.0))


poisson = Family(
    name="poisson",
    variance=lambda mu: mu,
    dev_resids=_pois_dev,
    loglik_terms=_pois_ll,
    init_mu=lambda y, wt: y + 0.1,
    default_link="log",
    dispersion_fixed=True,
)


# ----------------------------------------------------------------------------
# gamma
# ----------------------------------------------------------------------------

def _gamma_dev(y, mu, wt):
    yc = jnp.maximum(y, _EPS)
    return -2.0 * wt * (jnp.log(yc / jnp.maximum(mu, _EPS)) - (y - mu) / jnp.maximum(mu, _EPS))


def _gamma_ll(y, mu, wt):
    # Profile form used only for reporting; R's Gamma aic additionally
    # estimates shape by MLE — we report the moment-based version (documented
    # deviation; deviance/coefs are unaffected).
    return wt * (-y / jnp.maximum(mu, _EPS) - jnp.log(jnp.maximum(mu, _EPS)))


gamma = Family(
    name="gamma",
    variance=lambda mu: mu * mu,
    dev_resids=_gamma_dev,
    loglik_terms=_gamma_ll,
    init_mu=lambda y, wt: jnp.maximum(y, _EPS),
    default_link="inverse",
    dispersion_fixed=False,
)


# ----------------------------------------------------------------------------
# inverse gaussian
# ----------------------------------------------------------------------------

inverse_gaussian = Family(
    name="inverse_gaussian",
    variance=lambda mu: mu ** 3,
    dev_resids=lambda y, mu, wt: wt * (y - mu) ** 2 / (y * mu * mu),
    loglik_terms=lambda y, mu, wt: -0.5 * wt * (y - mu) ** 2 / (y * mu * mu),
    init_mu=lambda y, wt: jnp.maximum(y, _EPS),
    default_link="inverse_squared",
    dispersion_fixed=False,
)


# ----------------------------------------------------------------------------
# quasi families (R's quasipoisson/quasibinomial): same mean/variance model,
# dispersion estimated by Pearson chi^2 / df instead of fixed at 1, AIC
# undefined (R reports NA)
# ----------------------------------------------------------------------------

_NAN_AIC = lambda dev, ll, n, p, wt_sum: jnp.nan

quasipoisson = dataclasses.replace(
    poisson, name="quasipoisson", dispersion_fixed=False, aic=_NAN_AIC)
quasibinomial = dataclasses.replace(
    binomial, name="quasibinomial", dispersion_fixed=False, aic=_NAN_AIC)


FAMILIES: dict[str, Family] = {
    "gaussian": gaussian,
    "binomial": binomial,
    "poisson": poisson,
    "gamma": gamma,
    "inverse_gaussian": inverse_gaussian,
    "quasipoisson": quasipoisson,
    "quasibinomial": quasibinomial,
}


def get_family(family: str | Family) -> Family:
    if isinstance(family, Family):
        return family
    try:
        return FAMILIES[family.lower()]
    except KeyError:
        raise ValueError(
            f"unknown family {family!r}; available: {sorted(FAMILIES)}") from None


def resolve(family: str | Family, link: str | Link | None) -> tuple[Family, Link]:
    fam = get_family(family)
    lnk = get_link(link if link is not None else fam.default_link)
    return fam, lnk
