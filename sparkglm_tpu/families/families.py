"""Exponential-family records for IRLS.

The reference declares a ``family`` string but implements only binomial —
every other family's dispatch falls through to the binomial fitter
(/root/reference/src/main/scala/com/Alteryx/sparkGLM/GLM.scala:486-490,
586-590).  SURVEY.md §7 makes gaussian/poisson/gamma (plus inverse-gaussian)
mandatory; building the general ``Family`` record is *less* code than the
reference's per-link copy-paste.

Each family provides pure element-wise jnp functions (fused by XLA into the
IRLS step):
  * ``variance(mu)`` — V(mu)                 (ref: varianceBinomial GLM.scala:125-129)
  * ``dev_resids(y, mu, wt)`` — per-row deviance contributions
                                              (ref: devBinomial GLM.scala:162-170)
  * ``init_mu(y, wt)`` — IRLS starting mean  (ref: ybar*ones GLM.scala:420-424)
  * ``aic(dev, loglik, n, p, wt_sum)``        (ref: createObj GLM.scala:59-88,
                                               aic = -2 ll + 2 p)

Log-likelihoods (ref: llBinomial GLM.scala:132-143) are NOT device code:
reported statistics are computed in host float64 (models/hoststats.py) from
the final linear predictor, because TPU f32 transcendentals are too
approximate for R-parity scalars.  The jnp functions here are what the
compiled IRLS loop itself needs: variance, deviance (convergence), init.

Conventions follow R's ``glm`` (the reference's stated oracle, SURVEY.md §4):
for binomial with group sizes m, ``y`` is the *proportion* of successes and
``wt`` carries m (the reference's ``m`` argument, GLM.scala:254-315); the
top-level ``glm()`` front-end converts counts+m into this form.
"""

from __future__ import annotations

import dataclasses
import types as _types
from typing import Callable

import jax.numpy as jnp
import numpy as np

from .links import Link, get_link

_EPS = 1e-10


def _ylogyd(y, mu):
    """y * log(y/mu) with 0*log(0) = 0, as a SINGLE log of a near-1 ratio.

    Deviance formulas must not expand this into xlogy(y,y) - xlogy(y,mu):
    those two terms are each O(y*log y) and cancel to O(residual), so the
    TPU's few-ulp f32 ``log`` error gets amplified ~100x (measured 2.5e-4
    relative deviance error on the Dobson fixture vs 1e-6 in ratio form)."""
    return jnp.where(
        y == 0.0, 0.0,
        y * jnp.log(jnp.maximum(y, _EPS) / jnp.maximum(mu, _EPS)))


@dataclasses.dataclass(frozen=True, eq=False)
class Family:
    name: str
    variance: Callable
    dev_resids: Callable          # (y, mu, wt[, param]) -> per-row deviance
    init_mu: Callable             # (y, wt[, param]) -> mu0 per row
    default_link: str
    dispersion_fixed: bool        # True: dispersion == 1 (binomial, poisson)
    # aic(dev_total, loglik_total, n_obs, n_params, wt) -> scalar; the ll
    # argument is the exact host-f64 R logLik from models/hoststats.py
    aic: Callable = None  # type: ignore[assignment]
    # numeric family parameter (NB theta): the device callables then take
    # it as their LAST argument, and it flows through the IRLS kernels as
    # a TRACED operand — so glm.nb's theta search reuses ONE compiled
    # kernel across every theta value instead of retracing per round.
    # robustreg pseudo-families carry a LENGTH-2 param (shape, eps): the
    # smoothing eps shrinks across host passes without recompiling.
    param: object | None = None
    # robust(y, mu, wt, param) -> per-row multiplicative weight on W (the
    # reweighting rule that turns gaussian IRLS into quantile/Huber/l1
    # pseudo-likelihood fitting, arXiv 1902.06391).  ``wt`` is the prior
    # weight vector — the linf rule needs it to mask padding rows out of
    # its row-GLOBAL softmax.  None for every genuine exponential family —
    # ops/fused.py::irls_weights applies it only when present, so existing
    # jaxprs are untouched.
    robust: Callable | None = None

    def __post_init__(self):
        if self.aic is None:
            object.__setattr__(
                self, "aic",
                lambda dev, ll, n, p, wt_sum: -2.0 * ll + 2.0 * p)

    # jit static-arg identity: the DEVICE callables + the flags that shape
    # the compiled program — NOT the name, NOT the param VALUE (parametric
    # families share one kernel; the param is a traced input), NOT the
    # host-side aic.  Module-level callables make equal-math families
    # (e.g. every negative_binomial(theta)) hash equal.
    def _static_key(self):
        return (self.variance, self.dev_resids, self.init_mu,
                self.dispersion_fixed, self.param is None, self.robust)

    def __hash__(self):
        return hash(self._static_key())

    def __eq__(self, other):
        return (isinstance(other, Family)
                and self._static_key() == other._static_key())

    def param_operand(self, dtype=None):
        """The traced operand kernels thread through as ``fam_param`` —
        None for parameterless families.  The ONE place the binding rule
        lives (review r3)."""
        if self.param is None:
            return None
        return (jnp.asarray(self.param, dtype) if dtype is not None
                else self.param)

    def with_param(self, param):
        """Bind a TRACED param to the callables (no-op when the family has
        none) — what the kernels call instead of touching ``param``
        directly, so the value never enters the jaxpr as a constant."""
        if self.param is None:
            return self
        if param is None:
            # a call path forgot to thread fam_param: fail clearly at the
            # boundary instead of a TypeError deep inside the math
            raise ValueError(
                f"family {self.name!r} is parametric; pass its traced "
                "parameter (fam_param=family.param_operand(...)) to the "
                "kernel")
        return _types.SimpleNamespace(
            variance=lambda mu: self.variance(mu, param),
            dev_resids=lambda y, mu, wt: self.dev_resids(y, mu, wt, param),
            init_mu=lambda y, wt: self.init_mu(y, wt, param),
            robust=(None if self.robust is None
                    else lambda y, mu, wt: self.robust(y, mu, wt, param)))


# ----------------------------------------------------------------------------
# gaussian
# ----------------------------------------------------------------------------

gaussian = Family(
    name="gaussian",
    variance=lambda mu: jnp.ones_like(mu),
    dev_resids=lambda y, mu, wt: wt * (y - mu) ** 2,
    init_mu=lambda y, wt: y,
    default_link="identity",
    dispersion_fixed=False,
    # R: gaussian()$aic + 2*rank = n*(log(2*pi*dev/n)+1) + 2 - sum(log wt)
    # + 2*p, i.e. -2*logLik + 2*(p+1): the estimated sigma^2 is a parameter
    aic=lambda dev, ll, n, p, wt_sum: -2.0 * ll + 2.0 * (p + 1.0),
)


# ----------------------------------------------------------------------------
# binomial  (y = proportion successes, wt = group size m * prior weight)
# ----------------------------------------------------------------------------

def _binom_dev(y, mu, wt):
    # 2*wt*[y log(y/mu) + (1-y) log((1-y)/(1-mu))], each as a single
    # ratio-log (see _ylogyd) — the reference guards only via max(y,1) on
    # counts (GLM.scala:167).
    return 2.0 * wt * (_ylogyd(y, mu) + _ylogyd(1.0 - y, 1.0 - mu))


binomial = Family(
    name="binomial",
    variance=lambda mu: mu * (1.0 - mu),
    dev_resids=_binom_dev,
    # R's binomial initialize: mustart = (wt*y + 0.5)/(wt + 1)
    init_mu=lambda y, wt: (wt * y + 0.5) / (wt + 1.0),
    default_link="logit",
    dispersion_fixed=True,
)


# ----------------------------------------------------------------------------
# poisson
# ----------------------------------------------------------------------------

def _pois_dev(y, mu, wt):
    return 2.0 * wt * (_ylogyd(y, mu) - (y - mu))


poisson = Family(
    name="poisson",
    variance=lambda mu: mu,
    dev_resids=_pois_dev,
    init_mu=lambda y, wt: y + 0.1,
    default_link="log",
    dispersion_fixed=True,
)


# ----------------------------------------------------------------------------
# gamma
# ----------------------------------------------------------------------------

def _gamma_dev(y, mu, wt):
    # R Gamma()$dev.resids: -2*wt*(log(ifelse(y==0, 1, y/mu)) - (y-mu)/mu).
    # The y==0 guard matters for quasi(mu^2), which R permits on zero
    # responses (Gamma itself rejects them at init; so do we) — an epsilon
    # clamp here would add ~log(eps) ~ -690 per zero row to the deviance.
    mu_c = jnp.maximum(mu, _EPS)
    ratio = jnp.where(y == 0, 1.0, y / mu_c)
    return -2.0 * wt * (jnp.log(ratio) - (y - mu) / mu_c)


gamma = Family(
    name="gamma",
    variance=lambda mu: mu * mu,
    dev_resids=_gamma_dev,
    init_mu=lambda y, wt: jnp.maximum(y, _EPS),
    default_link="inverse",
    dispersion_fixed=False,
    # -2*logLik + 2*(p+1): R's Gamma()$aic "+2" is the dispersion parameter
    # (exact logLik with R's disp = dev/sum(wt) plug-in: hoststats.loglik)
    aic=lambda dev, ll, n, p, wt_sum: -2.0 * ll + 2.0 * (p + 1.0),
)


# ----------------------------------------------------------------------------
# inverse gaussian
# ----------------------------------------------------------------------------

inverse_gaussian = Family(
    name="inverse_gaussian",
    variance=lambda mu: mu ** 3,
    dev_resids=lambda y, mu, wt: wt * (y - mu) ** 2 / (y * mu * mu),
    init_mu=lambda y, wt: jnp.maximum(y, _EPS),
    default_link="inverse_squared",
    dispersion_fixed=False,
    # R inverse.gaussian()$aic + 2*rank, i.e. -2*logLik + 2*(p+1) with the
    # exact logLik (incl. the 3*sum(wt*log y) constant) from hoststats
    aic=lambda dev, ll, n, p, wt_sum: -2.0 * ll + 2.0 * (p + 1.0),
)


# ----------------------------------------------------------------------------
# quasi families (R's quasipoisson/quasibinomial): same mean/variance model,
# dispersion estimated by Pearson chi^2 / df instead of fixed at 1, AIC
# undefined (R reports NA)
# ----------------------------------------------------------------------------

_NAN_AIC = lambda dev, ll, n, p, wt_sum: jnp.nan

quasipoisson = dataclasses.replace(
    poisson, name="quasipoisson", dispersion_fixed=False, aic=_NAN_AIC)
quasibinomial = dataclasses.replace(
    binomial, name="quasibinomial", dispersion_fixed=False, aic=_NAN_AIC)

# ----------------------------------------------------------------------------
# negative binomial with KNOWN theta — MASS::negative.binomial(theta): a
# proper one-parameter GLM family (variance mu + mu^2/theta); glm_nb
# (models/negbin.py) wraps it with the ML theta estimation loop
# ----------------------------------------------------------------------------

def _nb_variance(mu, theta):
    return mu + mu * mu / theta


def _nb_dev_resids(y, mu, wt, theta):
    mu_c = jnp.maximum(mu, _EPS)
    return 2.0 * wt * (
        _ylogyd(y, mu_c)
        - (y + theta) * jnp.log((y + theta) / (mu_c + theta)))


def _nb_init_mu(y, wt, theta):
    # MASS negative.binomial()$initialize: mustart = y + (y == 0)/6
    return y + (y == 0) / 6.0


def _nb_aic(dev_, ll, n, p, wt_sum):
    return -2.0 * ll + 2.0 * (p + 1.0)


def negative_binomial(theta: float) -> Family:
    """MASS's ``negative.binomial(theta)`` family (fixed shape ``theta``).

    Deviance residuals are MASS's: 2*wt*(y*log(max(y,1)/mu)
    - (y+theta)*log((y+theta)/(mu+theta))); variance mu + mu^2/theta;
    default link log; dispersion fixed at 1 (glm.nb reports "dispersion
    parameter ... taken to be 1"); AIC = -2*logLik + 2*(p+1) — glm.nb
    counts the estimated theta as a parameter.

    theta rides the kernels as a TRACED param (module-level callables +
    Family's value-free static key), so glm.nb's theta alternation
    compiles the IRLS while_loop exactly once.
    """
    th = float(theta)
    if not np.isfinite(th) or th <= 0:
        raise ValueError(f"theta must be positive and finite, got {theta!r}")

    return Family(
        name=f"negative_binomial({th:.10g})",
        variance=_nb_variance,
        dev_resids=_nb_dev_resids,
        init_mu=_nb_init_mu,
        default_link="log",
        dispersion_fixed=True,
        aic=_nb_aic,
        param=th,
    )


def nb_theta(name: str) -> float | None:
    """The fixed shape of a ``negative_binomial(<theta>)`` family name, else
    None — the single parser for the name format ``negative_binomial``
    emits (get_family, models/hoststats.py and models/negbin.py all route
    through here)."""
    if name.startswith("negative_binomial(") and name.endswith(")"):
        return float(name[len("negative_binomial("):-1])
    return None


_QUASI_VARIANCE_BASE = {
    "constant": lambda: gaussian,
    "mu": lambda: poisson,
    "mu(1-mu)": lambda: binomial,
    "mu^2": lambda: gamma,
    "mu^3": lambda: inverse_gaussian,
}


def quasi(variance: str = "constant") -> Family:
    """R's general ``quasi(variance=...)`` family constructor.

    The variance function selects the mean/variance model (and with it the
    quasi-deviance — R's quasi() uses exactly the matching exponential
    family's deviance residuals); dispersion is estimated (Pearson/df) and
    AIC/logLik are NA, as in R.  Combine with any link via the separate
    ``link=`` argument (R's quasi default link is "identity"):

        sg.glm_fit(X, y, family=sg.quasi("mu^2"), link="log")
    """
    try:
        base = _QUASI_VARIANCE_BASE[variance]()
    except KeyError:
        raise ValueError(
            f"unknown quasi variance {variance!r}; choose from "
            f"{sorted(_QUASI_VARIANCE_BASE)}") from None
    return dataclasses.replace(
        base, name=f"quasi({variance})", default_link="identity",
        dispersion_fixed=False, aic=_NAN_AIC)


FAMILIES: dict[str, Family] = {
    "gaussian": gaussian,
    "binomial": binomial,
    "poisson": poisson,
    "gamma": gamma,
    "inverse_gaussian": inverse_gaussian,
    "quasipoisson": quasipoisson,
    "quasibinomial": quasibinomial,
}


def get_family(family: str | Family) -> Family:
    if isinstance(family, Family):
        return family
    name = family.lower()
    # "quasi(mu^2)" round-trips through model metadata (serialize.py stores
    # the name string); "quasi" alone is R's default variance="constant"
    if name == "quasi":
        return quasi()
    if name.startswith("quasi(") and name.endswith(")"):
        return quasi(name[len("quasi("):-1])
    th = nb_theta(name)
    if th is not None:
        return negative_binomial(th)
    if name.split("(")[0] in ("quantile", "huber", "l1", "linf"):
        # robust pseudo-families (sparkglm_tpu/robustreg) — lazy import to
        # keep families free of a robustreg dependency cycle
        from ..robustreg.pseudo import robust_family
        return robust_family(name)
    try:
        return FAMILIES[name]
    except KeyError:
        raise ValueError(
            f"unknown family {family!r}; available: "
            f"{sorted(FAMILIES) + ['quasi(<variance>)']}, robust: "
            "'quantile(<tau>)', 'huber[(k)]', 'l1', 'linf'") from None


def resolve(family: str | Family, link: str | Link | None) -> tuple[Family, Link]:
    fam = get_family(family)
    lnk = get_link(link if link is not None else fam.default_link)
    return fam, lnk
