from .mesh import (DATA_AXIS, MODEL_AXIS, make_mesh, pad_mask, padded_rows,
                   replicate, row_spec, shard_rows, single_device_mesh)
