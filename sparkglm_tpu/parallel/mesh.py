"""Device mesh + row-sharding utilities.

TPU-native replacement for the reference's distributed-matrix container:
ml-matrix ``RowPartitionedMatrix`` (used at
/root/reference/src/main/scala/com/Alteryx/sparkGLM/utils.scala:36-39 and
LM.scala:220-221).  A "row-partitioned matrix" here is simply a
``jax.Array`` laid out with ``NamedSharding(mesh, P("data", ...))`` over a
named device mesh; partition alignment (the reference's ``RDD.zip``,
GLM.scala:365-367) is free because every per-row tensor shares the same
sharding.

Two mesh axes:
  * ``"data"``  — row (observation) sharding; the reference's only strategy.
  * ``"model"`` — optional feature-axis sharding (tensor parallelism) for
    very wide designs; size 1 by default.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def shard_map(f, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions: the public alias (jax >= 0.5,
    ``check_vma``) or the experimental module (jax < 0.5, ``check_rep``) —
    replication checking disabled either way, matching every caller here."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False)


def make_mesh(
    n_data: int | None = None,
    n_model: int = 1,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a ``(data, model)`` mesh over the available devices."""
    if devices is None:
        devices = jax.devices()
    n_dev = len(devices)
    if n_data is None:
        if n_dev % n_model:
            raise ValueError(f"{n_dev} devices not divisible by n_model={n_model}")
        n_data = n_dev // n_model
    need = n_data * n_model
    if need > n_dev:
        raise ValueError(f"mesh {n_data}x{n_model} needs {need} devices, have {n_dev}")
    dev_grid = np.asarray(devices[:need]).reshape(n_data, n_model)
    return Mesh(dev_grid, (DATA_AXIS, MODEL_AXIS))


def single_device_mesh() -> Mesh:
    """A 1x1 mesh — the analogue of the reference's npart==1 fast path
    (LM.scala:254, GLM.scala:613-617); same code path, trivial collectives."""
    return make_mesh(n_data=1, n_model=1, devices=jax.devices()[:1])


def row_spec(ndim: int, shard_features: bool = False) -> P:
    """PartitionSpec for a row-sharded array: rows on "data", features on
    "model" when ``shard_features`` (only meaningful for ndim >= 2)."""
    if ndim == 1:
        return P(DATA_AXIS)
    trailing = (MODEL_AXIS,) if shard_features else (None,) * (ndim - 1)
    return P(DATA_AXIS, *trailing)


def replicated_spec() -> P:
    return P()


def padded_rows(n: int, mesh: Mesh) -> int:
    """Rows after padding ``n`` up to a multiple of the data-axis size."""
    d = mesh.shape[DATA_AXIS]
    return ((n + d - 1) // d) * d


def shard_rows(
    x: np.ndarray | jax.Array,
    mesh: Mesh,
    *,
    shard_features: bool = False,
    pad_value: float = 0.0,
) -> jax.Array:
    """Place an array on the mesh, row-sharded, zero-padding the row axis to a
    multiple of the data-axis size.

    Padded rows are made inert by giving them zero *weight* in every fit (the
    WLS core always carries a per-row weight vector, so a zero-weight row
    contributes nothing to X'WX, X'Wz, deviance, or SSE).  Callers that build
    weights themselves must use :func:`pad_mask`.

    A ``StructuredDesign`` (data/structured.py) shards leaf-wise: the dense
    block zero-pads like any matrix and each index vector pads with the
    factor's TRASH bucket (L — sliced off every segment sum), so pad rows
    touch no real level even before their zero weight makes every
    contribution exactly zero (ops/factor_gramian.py).  A ``SparseDesign``
    (data/sparse.py) does the same with its ELL slots: pad rows carry the
    sparse trash column (n_sparse) with value 0.
    """
    from ..data.sparse import SparseDesign
    from ..data.structured import StructuredDesign
    if isinstance(x, StructuredDesign):
        if shard_features:
            raise ValueError(
                "structured designs cannot be feature-sharded — densify "
                "first or use shard_features=False")
        return StructuredDesign(
            shard_rows(x.dense, mesh, pad_value=pad_value),
            tuple(shard_rows(ix, mesh, pad_value=L)
                  for (_, L), ix in zip(x.layout.factors, x.idx)),
            x.layout)
    if isinstance(x, SparseDesign):
        if shard_features:
            raise ValueError(
                "sparse designs cannot be feature-sharded — densify "
                "first or use shard_features=False")
        return SparseDesign(
            shard_rows(x.dense, mesh, pad_value=pad_value),
            shard_rows(x.cols, mesh, pad_value=x.layout.n_sparse),
            shard_rows(x.vals, mesh, pad_value=0.0),
            x.layout)
    x = np.asarray(x)
    n = x.shape[0]
    n_pad = padded_rows(n, mesh)
    if n_pad != n:
        pad_width = [(0, n_pad - n)] + [(0, 0)] * (x.ndim - 1)
        x = np.pad(x, pad_width, constant_values=pad_value)
    spec = row_spec(x.ndim, shard_features)
    return jax.device_put(x, NamedSharding(mesh, spec))


def replicate(x, mesh: Mesh) -> jax.Array:
    return jax.device_put(np.asarray(x), NamedSharding(mesh, P()))


def pad_mask(n: int, mesh: Mesh, dtype=np.float32) -> np.ndarray:
    """1.0 for real rows, 0.0 for padding rows (host-side; shard it with
    :func:`shard_rows`)."""
    n_pad = padded_rows(n, mesh)
    m = np.zeros((n_pad,), dtype=dtype)
    m[:n] = 1.0
    return m
