"""Multi-host (multi-process) setup: the framework's communication backend.

The reference's distributed story is Spark's driver/executor runtime with
Akka/Netty RPC + shuffle transport (SURVEY.md §2.4): `treeReduce` for the
(p x p, p) Gramian pairs, `collect.reduce` for scalars, `RDD.zip` for
partition alignment.  Here the backend is XLA's collectives over ICI within
a slice and DCN across slices: every reduction in the fit kernels is a
`lax.psum` on the `"data"` mesh axis, and alignment is free because all
per-row arrays share one `NamedSharding`.

This module provides the process-level glue those kernels need on a real
multi-host pod:

  * :func:`initialize` — `jax.distributed.initialize` wrapper (controller
    discovery, process ids), idempotent and a no-op single-process.
  * :func:`global_mesh` — a Mesh over ALL processes' devices, data axis
    ordered so each host's addressable devices are contiguous (its rows
    stay host-local).
  * :func:`host_shard_to_global` — assemble a global row-sharded array from
    per-host shards (each host passes only ITS rows, e.g. from
    ``read_csv(path, shard_index=process_index(), num_shards=process_count())``)
    via `jax.make_array_from_process_local_data` — the no-driver-collect
    analogue of the reference's `dataFrameToMatrix` (utils.scala:36-39).

Typical multi-host flow::

    import sparkglm_tpu as sg
    from sparkglm_tpu.parallel import distributed as dist

    dist.initialize()                       # once per process
    mesh = dist.global_mesh()
    schema = sg.scan_csv_schema(path)       # same result on every host
    levels = sg.scan_csv_levels(path)       # GLOBAL factor levels (one pass)
    cols = sg.read_csv(path, shard_index=dist.process_index(),
                       num_shards=dist.process_count(), schema=schema)
    terms = sg.build_terms(cols, predictors, intercept=True, levels=levels)
    X = sg.transform(cols, terms)           # identical design on every host
    y = cols[target]
    tgt = dist.sync_max_rows(X.shape[0], mesh)
    Xp, w = dist.pad_host_shard(X, tgt)     # zero-weight padding rows
    yp, _ = dist.pad_host_shard(y.astype(X.dtype), tgt)
    Xg = dist.host_shard_to_global(Xp, mesh)
    yg = dist.host_shard_to_global(yp, mesh)
    wg = dist.host_shard_to_global(w, mesh)
    model = sg.glm_fit(Xg, yg, weights=wg, family="binomial", mesh=mesh)

Single-chip / CPU-mesh sessions can use everything here too — each helper
degrades to the local equivalent.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as meshlib

_initialized = False


# env vars whose presence indicates a managed multi-process environment that
# jax.distributed.initialize() can auto-detect (cloud TPU pods, SLURM, ...)
_CLUSTER_ENV_VARS = (
    "COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS", "SLURM_JOB_ID",
)


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Join the multi-process JAX runtime (idempotent).

    With explicit arguments, calls ``jax.distributed.initialize`` directly —
    this MUST run before any other JAX API touches a backend (we deliberately
    do not query ``jax.process_count()`` first, which would initialize one).
    With no arguments, auto-detection runs only when a recognised cluster
    environment variable is present; otherwise this is a single-process
    no-op.
    """
    global _initialized
    if _initialized:
        return
    import os
    explicit = (coordinator_address is not None or num_processes is not None
                or process_id is not None)
    if explicit:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    elif any(os.environ.get(v) for v in _CLUSTER_ENV_VARS):
        try:
            jax.distributed.initialize()  # environment auto-detection
        except ValueError:
            pass  # heuristic misfired: no resolvable coordinator -> local
    _initialized = True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def global_mesh(n_model: int = 1) -> Mesh:
    """A (data, model) mesh over every device of every process.

    `jax.devices()` orders devices so each process's addressable devices
    are grouped; keeping that order on the data axis means each host's row
    shard lives on its own chips — collectives ride ICI/DCN, host->device
    transfers stay local.
    """
    return meshlib.make_mesh(n_model=n_model, devices=jax.devices())


def host_shard_to_global(local_rows: np.ndarray, mesh: Mesh) -> jax.Array:
    """Build a global row-sharded jax.Array from this process's rows.

    Every process passes its own (n_local, ...) block; the result behaves
    as the (sum n_local, ...) concatenation, row-sharded over the mesh's
    data axis.  Row counts must be equal across processes (pad the last
    host's shard with zero-weight rows if the byte-range split was uneven).
    """
    local_rows = np.asarray(local_rows)
    spec = meshlib.row_spec(local_rows.ndim)
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return meshlib.shard_rows(local_rows, mesh)
    # catch divergent per-host designs BEFORE they misalign the global
    # Gramian: every process must agree on the trailing (feature) shape —
    # e.g. a CSV shard missing a factor level dummy-codes fewer columns
    # (ADVICE r1; pass scan_csv_levels to build_terms to avoid it)
    sig = np.asarray([local_rows.ndim] + list(local_rows.shape[1:]), np.int64)
    from jax.experimental import multihost_utils as mh
    sigs = np.asarray(mh.process_allgather(sig.astype(np.int32)))
    if not (sigs == sigs[0]).all():
        raise ValueError(
            "host shards disagree on the feature dimension: "
            f"{[list(s) for s in sigs]} (ndim, trailing shape) — did each "
            "host build its model matrix from locally discovered factor "
            "levels? Use scan_csv_levels + build_terms(levels=...) so every "
            "host codes the same design, and compare Terms.signature().")
    return jax.make_array_from_process_local_data(sharding, local_rows)


def allsum_f64(values) -> np.ndarray:
    """Sum a small float64 host vector across processes.

    Transport rides a jax allgather, which truncates to f32 when x64 is
    off (the TPU default) — so each value travels as an (hi, lo) float32
    pair and recombines to ~2^-48 relative accuracy.  This is how the
    host-f64 reported statistics (models/hoststats.py) stay R-exact on a
    multi-host fit.  Single-process: identity.
    """
    v = np.atleast_1d(np.asarray(values, np.float64))
    if jax.process_count() == 1:
        return v
    from jax.experimental import multihost_utils as mh
    hi = v.astype(np.float32)
    lo = (v - hi).astype(np.float32)
    g = np.asarray(mh.process_allgather(np.stack([hi, lo])), np.float64)
    return np.sum(g[:, 0, :] + g[:, 1, :], axis=0)


def sync_max_rows(n_local: int, mesh: Mesh | None = None) -> int:
    """Agree on a common per-host row count — the max across processes,
    rounded up so the GLOBAL row count divides evenly over the mesh's data
    axis (host_shard_to_global requires both equal per-host counts and an
    even device split).  Pad the difference with zero-weight rows
    (:func:`pad_host_shard`)."""
    if jax.process_count() == 1:
        n = int(n_local)
    else:
        from jax.experimental import multihost_utils as mh
        g = np.asarray(mh.process_allgather(np.asarray([n_local], np.int32)))
        n = int(g.max())
    if mesh is not None:
        d_local = max(1, mesh.shape[meshlib.DATA_AXIS] // jax.process_count())
        n = ((n + d_local - 1) // d_local) * d_local
    return n


def local_rows_of(global_array: jax.Array) -> np.ndarray:
    """This process's rows of a row-sharded global array, in global row
    order (deduplicated when a model axis replicates row shards)."""
    seen = {}
    for s in global_array.addressable_shards:
        idx = s.index[0]
        start = 0 if idx.start is None else int(idx.start)
        if start not in seen:
            seen[start] = np.asarray(s.data)
    return np.concatenate([seen[k] for k in sorted(seen)], axis=0)


def pad_host_shard(local_rows: np.ndarray, target_rows: int,
                   weights: np.ndarray | None = None):
    """Pad this host's shard to ``target_rows`` with zero-weight rows so
    all hosts agree on the global shape (returns padded array + weights)."""
    local_rows = np.asarray(local_rows)
    n = local_rows.shape[0]
    if target_rows < n:
        raise ValueError(f"target_rows={target_rows} < local rows {n}")
    if weights is None:
        wdt = (local_rows.dtype
               if np.issubdtype(local_rows.dtype, np.floating) else np.float32)
        w = np.ones((n,), wdt)
    else:
        w = np.asarray(weights)  # keep the caller's dtype (f64 stays f64)
    if target_rows == n:
        return local_rows, w
    pad = [(0, target_rows - n)] + [(0, 0)] * (local_rows.ndim - 1)
    return (np.pad(local_rows, pad),
            np.pad(w, (0, target_rows - n)))
