"""The capability lattice, declared in ONE place.

Every combination the system refuses lives in this module's tables; the
front-end guard functions (api.py ``_reject_*``) are thin translators
into :func:`check_penalized` / :func:`check_elastic` / :func:`check_fleet`
and every refusal raises the same typed error, :class:`CapabilityError`
(a ``ValueError`` — existing ``pytest.raises(ValueError, match=...)``
callers keep working, and the reason text is preserved verbatim).

Two layers:

  * The 4-axis LATTICE — design x Gramian engine x penalty x execution —
    declared in :data:`LATTICE_RULES` and queried by :func:`refusal`.
    A cell with no matching rule FITS; a matching rule carries the
    pointed reason (why the combination is genuinely impossible or not
    yet built, and what to do instead).  ``tests/test_fleet_lattice.py``
    walks every cell and asserts fit-or-pointed-error — no silent
    ignores.
  * OPTION rules — keyword combinations with no lattice meaning
    (``beta0=`` on a path fit, ``resume=`` on the elastic scheduler…)
    that the per-front-end check functions refuse with the same error
    type.

Vocabulary: the lattice speaks the paper's axis names.  ``engine="exact"``
is the einsum/fused/qr exact-Gramian family, ``"segment-sum"`` is the
factor-aware Gramian a structured design runs (the two are one choice:
naming either implies the other), ``"sketch"`` is the r13
sketch-and-precondition engine.  ``execution="mesh"`` is a row-sharded
solo fit; a MEMBER-sharded fleet (``glm_fleet(mesh=)``) is the fleet
execution with the ``mesh`` option, checked by :func:`check_fleet`.
"""

from __future__ import annotations

__all__ = ["AXES", "LATTICE_RULES", "CapabilityError", "refusal", "check",
           "check_penalized", "check_elastic", "check_fleet", "lattice",
           "capability_lattice", "capability_refusal"]

AXES = dict(
    design=("dense", "structured", "sparse"),
    engine=("exact", "segment-sum", "sketch"),
    penalty=("none", "elastic-net"),
    execution=("solo", "fleet", "streaming", "elastic", "mesh"),
)


class CapabilityError(ValueError):
    """A refused capability-lattice cell.

    One typed format for every refusal: ``cell`` (the axis/option values
    that matched), ``reason`` (the pointed explanation, always naming the
    supported alternative).  ``str(e)`` carries both.
    """

    def __init__(self, cell: dict, reason: str):
        self.cell = dict(cell)
        self.reason = str(reason)
        tag = " ".join(f"{k}={v}" for k, v in self.cell.items())
        super().__init__(f"unsupported capability [{tag}]: {reason}")


def _matches(cell: dict, when: dict) -> bool:
    for k, v in when.items():
        alts = v if isinstance(v, tuple) else (v,)
        if cell.get(k) not in alts:
            return False
    return True


# (when, reason) — FIRST matching rule wins; no match means the cell fits.
# Reasons keep the exact wording the front-ends have always raised (guard
# tests match substrings of them).
LATTICE_RULES: tuple[tuple[dict, str], ...] = (
    # -- design x engine structural identities ----------------------------
    (dict(engine="segment-sum", design=("dense", "sparse")),
     "segment-sum is the structured design's Gramian engine; a "
     "dense/sparse design has no factor segments to sum — use "
     "design='structured' or engine='exact'"),
    (dict(engine="exact", design="structured"),
     "design='structured' IS the segment-sum engine (a structured design "
     "always assembles its Gramian by factor segment sums); name the cell "
     "engine='segment-sum'"),
    (dict(engine="sketch", design="structured"),
     "engine='sketch' has no structured form — the per-iteration sketch "
     "draws row combinations, which densifies every factor block; fit "
     "with design='dense' or engine='segment-sum'"),
    # -- sketch engine ----------------------------------------------------
    (dict(engine="sketch", penalty="elastic-net"),
     "penalty= does not support engine='sketch': the coordinate-descent "
     "lambda path screens and checks KKT conditions against exact "
     "Gramian columns, and a sketched X'WX would bias every one of them "
     "— fit the penalized path with engine='auto'"),
    (dict(engine="sketch", execution="elastic"),
     "workers= (the elastic shard scheduler) does not support "
     "engine='sketch': the one-shot shard combine is Gramian-additive "
     "and needs exact per-shard X'WX — drop workers= to stream a "
     "sketched fit on a single controller"),
    (dict(engine="sketch", execution="mesh"),
     "engine='sketch' is single-controller: the per-iteration sketch "
     "draw has no row-sharded form yet — drop mesh= or use "
     "engine='auto'"),
    # -- penalty ----------------------------------------------------------
    (dict(penalty="elastic-net", execution="mesh"),
     "penalty= does not support mesh= (sharded penalized fits are not "
     "implemented yet) — drop mesh= and fit the path on a single "
     "controller"),
    (dict(penalty="elastic-net", execution="elastic"),
     "penalty= does not support engine='elastic' (the lambda path has no "
     "shard combine rule yet); fit the penalized path on a single "
     "controller"),
    # -- fleet ------------------------------------------------------------
    (dict(execution="fleet", design="structured"),
     "fleet fitting does not support design='structured': the "
     "segment-sum Gramian engine batches over factor levels, which "
     "conflicts with batching over the model axis — use the dense "
     "design (per-segment models are narrow)"),
    (dict(execution="fleet", design="sparse"),
     "fleet designs are stacked dense (K, n, p) arrays; a SparseDesign "
     "has no stacked form — densify per-segment designs (they are "
     "narrow) or fit solo"),
    (dict(design="sparse", penalty="elastic-net"),
     "penalized paths take dense or structured designs (the formula "
     "front-ends build both); a SparseDesign has no penalized entry "
     "point — densify or drop penalty="),
    # -- streaming --------------------------------------------------------
    (dict(execution="streaming", design="sparse",
          engine=("exact", "segment-sum")),
     "sparse chunk sources stream through the sketched solver only (the "
     "exact streaming Gramian accumulates dense chunk blocks) — pass "
     "engine='sketch'"),
    (dict(execution="streaming", design="structured"),
     "the streaming drivers parse dense chunk designs; structured "
     "factor designs are resident-only — fit resident with "
     "design='structured'"),
    (dict(execution="elastic", design=("structured", "sparse")),
     "the elastic shard scheduler combines exact dense per-shard "
     "Gramians; structured/sparse designs are single-controller — drop "
     "workers="),
    (dict(execution="mesh", design="sparse"),
     "sparse designs cannot be feature- or row-sharded (the ELL layout "
     "is single-device) — densify first or drop mesh="),
)


def refusal(*, design: str = "dense", engine: str = "exact",
            penalty: str = "none", execution: str = "solo") -> str | None:
    """The pointed reason the cell is refused, or None when it fits."""
    for ax, val in (("design", design), ("engine", engine),
                    ("penalty", penalty), ("execution", execution)):
        if val not in AXES[ax]:
            raise ValueError(f"{ax} must be one of {AXES[ax]}, got {val!r}")
    cell = dict(design=design, engine=engine, penalty=penalty,
                execution=execution)
    for when, reason in LATTICE_RULES:
        if _matches(cell, when):
            return reason
    return None


def check(**cell) -> None:
    """Raise :class:`CapabilityError` when the lattice refuses ``cell``."""
    r = refusal(**cell)
    if r is not None:
        full = dict(design="dense", engine="exact", penalty="none",
                    execution="solo")
        full.update(cell)
        raise CapabilityError(full, r)


def lattice():
    """Every (design, engine, penalty, execution) cell with its status —
    the doc matrix and the exhaustive-walk test both iterate this."""
    for d in AXES["design"]:
        for e in AXES["engine"]:
            for pn in AXES["penalty"]:
                for ex in AXES["execution"]:
                    yield (d, e, pn, ex), refusal(design=d, engine=e,
                                                  penalty=pn, execution=ex)


# public aliases (the package namespace re-exports these names)
capability_refusal = refusal
capability_lattice = lattice


def _opt(cell: dict, reason: str) -> None:
    raise CapabilityError(cell, reason)


# ---------------------------------------------------------------------------
# front-end check functions (what api.py's _reject_* wrappers call)


def check_penalized(*, mesh=None, engine: str = "auto", beta0=None,
                    on_iteration=None, checkpoint_every: int = 0,
                    prefetch: int = 0) -> None:
    """Guards for ``penalty=`` on the solo/streaming front-ends.

    ``retry=`` is NOT rejected (the penalized streaming drivers honor it
    on every chunk pass) and neither are ``checkpoint=``/``resume=`` (the
    drivers checkpoint at lambda-path boundaries and resume
    bit-identically; penalized/stream.py).
    """
    if mesh is not None:
        check(penalty="elastic-net", execution="mesh")
    if engine == "sketch":
        check(penalty="elastic-net", engine="sketch")
    if engine not in ("auto", "einsum"):
        _opt(dict(penalty="elastic-net", engine=engine),
             f"penalty= requires the einsum/structured Gramian engine; "
             f"engine={engine!r} does not apply to the penalized path")
    if beta0 is not None or on_iteration is not None or checkpoint_every:
        _opt(dict(penalty="elastic-net"),
             "penalty= does not support beta0=/on_iteration=/"
             "checkpoint_every= (the path warm-starts itself)")
    if prefetch:
        _opt(dict(penalty="elastic-net", execution="streaming"),
             "penalty= does not support prefetch= yet (path passes "
             "stream sequentially)")


def check_elastic(*, penalty=None, beta0=None, on_iteration=None,
                  resume: bool = False, engine: str = "elastic") -> None:
    """Guards for the elastic shard scheduler (``workers=`` /
    ``engine='elastic'``).  Everything else (retry=, checkpoint=,
    prefetch=, trace=, metrics=, mesh=) flows through to the shard
    fits."""
    if engine == "sketch":
        check(engine="sketch", execution="elastic")
    if penalty is not None:
        check(penalty="elastic-net", execution="elastic")
    if beta0 is not None or on_iteration is not None:
        _opt(dict(execution="elastic"),
             "engine='elastic' does not support beta0=/on_iteration= (the "
             "combine step warm-starts the polish pass itself)")
    if resume:
        _opt(dict(execution="elastic"),
             "engine='elastic' resumes implicitly from the checkpoint= "
             "shard directory after a restart; drop resume=")


def check_fleet(*, engine: str = "auto", penalty=None,
                design: str = "dense", mesh=None, beta0=None,
                on_iteration=None, checkpoint_every: int = 0,
                start=None) -> None:
    """Guards for :func:`sparkglm_tpu.glm_fleet`.

    ``engine='sketch'``, ``penalty=ElasticNet(...)`` and ``mesh=`` are
    LEGAL fleet axes (PR 20 — batched lambda-path, member-sharded mesh
    kernel, per-member sketch engine); what remains refused is declared
    here and nowhere else.
    """
    if engine == "elastic":
        _opt(dict(execution="fleet", engine="elastic"),
             "fleet fitting does not support engine='elastic': the fleet "
             "kernel already IS the parallel axis (one executable over "
             "all models); shard-parallel workers would nest parallelism "
             "to no benefit — drop engine='elastic'")
    if engine not in ("auto", "einsum", "sketch"):
        _opt(dict(execution="fleet", engine=engine),
             f"fleet fitting requires the einsum or sketch Gramian "
             f"engine; engine={engine!r} does not apply to the fleet "
             f"path")
    if design == "structured":
        check(execution="fleet", design="structured",
              engine="segment-sum")
    if penalty is not None:
        if engine == "sketch":
            check(penalty="elastic-net", engine="sketch")
        if mesh is not None:
            _opt(dict(execution="fleet", penalty="elastic-net"),
                 "penalized fleets run the batched lambda-path kernel on "
                 "a single device; mesh= sharding of the path kernel is "
                 "not implemented yet — drop mesh= or penalty=")
        if start is not None:
            _opt(dict(execution="fleet", penalty="elastic-net"),
                 "penalized fleets do not support start= (each member's "
                 "lambda path warm-starts itself point-to-point)")
    if beta0 is not None or on_iteration is not None or checkpoint_every:
        _opt(dict(execution="fleet"),
             "fleet fitting does not support beta0=/on_iteration=/"
             "checkpoint_every= (the fleet kernel runs all models to "
             "convergence in one pass) — to warm-start a refit pass "
             "stacked (K, p) coefficients via start= instead")
