"""PathModel — the fitted elastic-net lambda path.

A frozen record of the whole regularization path (coefficient matrix over
the descending lambda grid, per-lambda df / deviance / deviance-ratio)
plus :meth:`PathModel.select`, which collapses one path point into an
ORDINARY fitted model (:class:`~sparkglm_tpu.models.lm.LMModel` /
:class:`~sparkglm_tpu.models.glm.GLMModel`).  Selection is the bridge to
the rest of the system: a selected model predicts, serializes
(models/serialize.py — PathModel itself round-trips too), registers and
serves (serve/) exactly like an unpenalized fit.

Penalized models carry NO sampling-theory inference: std_errors are NaN
and ``cov_unscaled`` is None (the lasso's post-selection distribution is
not the Wald one), and GLM ``loglik``/``aic`` are NaN — the ``criterion=``
options of :meth:`select` use the standard path heuristics
``deviance + 2 df`` / ``deviance + log(n) df`` instead (documented in
PARITY.md r11), where df counts nonzero penalized coefficients plus the
intercept.
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["PathModel"]


@dataclasses.dataclass(frozen=True)
class PathModel:
    """Fitted elastic-net lambda path (largest lambda first)."""

    lambdas: np.ndarray          # (n_lambda,) descending
    alpha: float
    coefficients: np.ndarray     # (n_lambda, p) on the ORIGINAL scale
    df: np.ndarray               # (n_lambda,) nonzero penalized coefs
    deviance: np.ndarray         # (n_lambda,) raw-weight deviance
    dev_ratio: np.ndarray        # (n_lambda,) 1 - deviance/null_deviance
    null_deviance: float
    family: str
    link: str
    xnames: tuple
    yname: str
    n_obs: int
    n_ok: int                    # weights > 0 row count (R's "good" rows)
    n_params: int
    has_intercept: bool
    standardize: bool
    penalty: object              # the ElasticNet spec that produced this
    converged: bool
    kkt_clean: bool              # no unresolved strong-rule violations
    iterations: int              # total IRLS iterations over the path
    dispersion_fixed: bool | None = None
    kind: str = "glm"            # "lm" | "glm": what select() builds
    formula: str | None = None
    terms: object | None = None
    has_offset: bool = False
    offset_col: str | None = None
    weights_col: str | None = None
    m_col: str | None = None
    has_weights: bool = False
    has_m: bool = False
    fit_info: dict | None = None
    gramian_engine: str | None = None

    # -- path accessors ----------------------------------------------------

    def __len__(self) -> int:
        return int(len(self.lambdas))

    def lambda_index(self, lambda_: float) -> int:
        """Nearest grid index to ``lambda_`` (log-scale distance, matching
        the grid's geometry)."""
        lam = float(lambda_)
        if not np.isfinite(lam) or lam < 0:
            raise ValueError(f"lambda_ must be finite and >= 0, got {lambda_!r}")
        grid = np.maximum(np.asarray(self.lambdas, np.float64), 1e-300)
        return int(np.argmin(np.abs(np.log(grid) - np.log(max(lam, 1e-300)))))

    def coef(self, lambda_: float | None = None) -> np.ndarray:
        """The (n_lambda, p) coefficient matrix, or the row nearest a
        specific ``lambda_``."""
        if lambda_ is None:
            return self.coefficients
        return self.coefficients[self.lambda_index(lambda_)]

    def criterion_values(self, criterion: str = "aic") -> np.ndarray:
        """Per-lambda selection scores: ``deviance + k * df_total`` with
        k = 2 (aic) or log(n_ok) (bic) — the glmnet-style path heuristic,
        NOT a likelihood-exact information criterion (PARITY.md r11)."""
        if criterion not in ("aic", "bic"):
            raise ValueError(
                f"criterion must be 'aic' or 'bic', got {criterion!r}")
        k = 2.0 if criterion == "aic" else float(np.log(max(self.n_ok, 2)))
        df_total = self.df.astype(np.float64) + (1.0 if self.has_intercept
                                                 else 0.0)
        return np.asarray(self.deviance, np.float64) + k * df_total

    # -- selection ---------------------------------------------------------

    def select(self, lambda_: float | None = None,
               criterion: str | None = None):
        """Collapse one path point into an ordinary fitted model.

        Exactly one of ``lambda_`` (nearest grid point) or ``criterion``
        (``"aic"`` | ``"bic"``, minimized over the path) must be given.
        The result is a plain :class:`LMModel`/:class:`GLMModel` —
        predict/serialize/registry/Scorer all apply — with NaN standard
        errors (no post-selection inference) and the selection recorded
        in ``fit_info["penalized"]``."""
        if (lambda_ is None) == (criterion is None):
            raise ValueError(
                "pass exactly one of lambda_= or criterion='aic'|'bic'")
        if lambda_ is not None:
            i = self.lambda_index(lambda_)
        else:
            i = int(np.argmin(self.criterion_values(criterion)))
        return self._model_at(i, criterion=criterion)

    def _model_at(self, i: int, criterion: str | None = None):
        p = int(self.n_params)
        beta = np.asarray(self.coefficients[i], np.float64)
        nan_se = np.full(p, np.nan)
        df_used = int(self.df[i]) + (1 if self.has_intercept else 0)
        df_resid = max(int(self.n_ok) - df_used, 0)
        sel_info = {
            "penalized": {
                "alpha": float(self.alpha),
                "lambda": float(self.lambdas[i]),
                "lambda_index": int(i),
                "n_lambda": int(len(self.lambdas)),
                "criterion": criterion,
                "df": int(self.df[i]),
                "dev_ratio": float(self.dev_ratio[i]),
                "standardize": bool(self.standardize),
            }
        }
        common = dict(
            coefficients=beta, std_errors=nan_se, xnames=tuple(self.xnames),
            yname=self.yname, n_obs=int(self.n_obs), n_params=p,
            has_intercept=bool(self.has_intercept), n_shards=1,
            cov_unscaled=None, formula=self.formula, terms=self.terms,
            offset_col=self.offset_col, has_offset=bool(self.has_offset),
            weights_col=self.weights_col, has_weights=bool(self.has_weights),
            fit_info=sel_info, gramian_engine=self.gramian_engine)
        if self.kind == "lm":
            from ..models.lm import LMModel
            sse = float(self.deviance[i])
            sst = float(self.null_deviance)
            r2 = float(self.dev_ratio[i])
            dfm = max(df_used - (1 if self.has_intercept else 0), 0)
            sigma = float(np.sqrt(sse / df_resid)) if df_resid > 0 else float("nan")
            adj = (1.0 - (1.0 - r2) * (self.n_ok - (1 if self.has_intercept
                                                    else 0)) / df_resid
                   if df_resid > 0 else float("nan"))
            return LMModel(df_model=dfm, df_resid=df_resid, sse=sse,
                           sst=sst, r_squared=r2, adj_r_squared=float(adj),
                           sigma=sigma, f_statistic=float("nan"), **common)
        from ..models.glm import GLMModel
        disp = 1.0 if self.dispersion_fixed else float("nan")
        return GLMModel(
            family=self.family, link=self.link,
            deviance=float(self.deviance[i]),
            null_deviance=float(self.null_deviance),
            pearson_chi2=float("nan"), loglik=float("nan"),
            aic=float("nan"), dispersion=disp, df_residual=df_resid,
            df_null=int(self.n_ok) - (1 if self.has_intercept else 0),
            iterations=int(self.iterations), converged=bool(self.converged),
            tol=float(self.penalty.tol if self.penalty is not None else 1e-7),
            dispersion_fixed=self.dispersion_fixed, m_col=self.m_col,
            has_m=bool(self.has_m), **common)

    # -- reporting ---------------------------------------------------------

    def fit_report(self) -> dict:
        """Path-level fit telemetry: the tracer aggregate (when the fit ran
        traced) plus the path block (lambda range, total IRLS iterations,
        CD sweeps, compile count)."""
        rep = {
            "model": f"penalized_{self.kind}", "family": self.family,
            "link": self.link, "alpha": float(self.alpha),
            "n_lambda": int(len(self.lambdas)),
            "lambda_max": float(self.lambdas[0]) if len(self.lambdas) else None,
            "lambda_min": float(self.lambdas[-1]) if len(self.lambdas) else None,
            "df_max": int(self.df.max(initial=0)),
            "dev_ratio_max": float(np.max(self.dev_ratio, initial=0.0)),
            "iterations": int(self.iterations),
            "converged": bool(self.converged),
            "kkt_clean": bool(self.kkt_clean),
            "n_obs": int(self.n_obs), "n_params": int(self.n_params),
            "gramian_engine": self.gramian_engine,
        }
        if self.fit_info:
            rep.update(self.fit_info)
        return rep

    def __repr__(self) -> str:
        lam0 = float(self.lambdas[0]) if len(self.lambdas) else float("nan")
        lam1 = float(self.lambdas[-1]) if len(self.lambdas) else float("nan")
        return (f"PathModel({self.kind}, family={self.family!r}, "
                f"alpha={self.alpha:g}, n_lambda={len(self.lambdas)}, "
                f"lambda=[{lam0:.4g} .. {lam1:.4g}], "
                f"df_max={int(self.df.max(initial=0))}, "
                f"dev_ratio_max={float(np.max(self.dev_ratio, initial=0.0)):.4f})")
