"""Out-of-core elastic-net lambda paths.

Penalization operates on the ACCUMULATED weighted Gramian — the whole
point of routing it through the streaming engine (ISSUE 6 tentpole):
chunked ``*_from_csv`` fits and ``design="structured"`` chunks feed the
same standardized coordinate-descent solvers as resident fits.

Two drivers, mirroring the resident dispatch in ``path.py``:

  * :func:`lm_path_streaming` — gaussian/identity.  The quadratic
    objective never re-weights, so ONE chunked data pass accumulates
    ``(X'WX, X'Wz, X'W1, z'Wz, sum w)`` in host f64 (left-to-right, the
    streaming engine's determinism contract) and the entire path then
    runs on the Gramian via the compiled ``_gram_path_kernel`` — the
    out-of-core path costs one data pass plus p x p work.
  * :func:`glm_path_streaming` — general families.  The lambda loop and
    IRLS loop run on the host (each IRLS step needs a full data pass for
    the re-weighted Gramian), but every device step goes through a FIXED
    set of jitted pass flavors — stats/fisher/deviance chunk kernels with
    bucket-padded shapes (``models/streaming.py::_bucket_pad``) and the
    lambda-TRACED ``_cd_solve_kernel`` — so executable count stays
    constant in both the chunk count and the grid length (compile events
    via the ``_traced_call`` cache-delta idiom).

Strong-rule screening + KKT verification run on the host here (numpy on
p-vectors), with identical thresholds to the compiled resident scan.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import trace as _obs_trace
from ..ops.factor_gramian import design_colsum, design_gramian, design_matvec
from .path import (_ALPHA_FLOOR, _KKT_ROUNDS, _SD_FLOOR, _TINY,
                   _NULL_MAX_ITER, _NULL_TOL, _gram_path_kernel,
                   _cd_solve_kernel, _work, assemble_path_model,
                   intercept_col, resolve_penalty_vector)

__all__ = ["lm_path_streaming", "glm_path_streaming"]


# -- chunk pass kernels (one executable per flavor; weights are RAW here —
# linear accumulations normalize by the global weight sum on the host)

@functools.partial(jax.jit, static_argnames=("precision",))
def _stats_chunk_kernel(X, y, w, off, *, precision):
    """Gaussian accumulation chunk: raw-weight ``(X'WX, X'Wz, X'W1, z'Wz,
    sum w, rows w>0)`` with ``z = y - offset``.  Doubles as the GLM stats
    pass (only A's diagonal, s1 and wsum are read there)."""
    dt = X.dtype
    acc = jnp.float64 if dt == jnp.float64 else jnp.float32
    z = (y - off).astype(dt)
    A, b = design_gramian(X, z, w, accum_dtype=acc, precision=precision)
    s1 = design_colsum(X, w, accum_dtype=acc, precision=precision)
    wa = w.astype(acc)
    za = z.astype(acc)
    return dict(A=A.astype(acc), b=b.astype(acc), s1=s1.astype(acc),
                yty=jnp.sum(wa * za * za), wsum=jnp.sum(wa),
                n_ok=jnp.sum(w > 0.0))


_FAM_STATICS = ("family", "link", "precision")


@functools.partial(jax.jit, static_argnames=_FAM_STATICS + ("first",))
def _null_chunk_kernel(y, wt, off, b0, fam_param, *, family, link, first,
                       precision):
    """Intercept-only IRLS chunk: scalar partials ``(sum w, sum w z,
    deviance)`` at ``eta = b0 + offset`` (or the family init when
    ``first``).  O(n) — no design access."""
    family = family.with_param(fam_param)
    dt = y.dtype
    acc = jnp.float64 if dt == jnp.float64 else jnp.float32
    valid = wt > 0.0
    if first:
        mu = jnp.where(valid, family.init_mu(y, jnp.maximum(wt, _TINY)), 1.0)
        eta = link.link(mu)
    else:
        eta = b0 + off
        mu = jnp.where(valid, link.inverse(eta), 1.0)
    w, z, dev = _work(y, wt, wt, off, eta, mu, family, link)
    return dict(sw=jnp.sum(w.astype(acc)), swz=jnp.sum((w * z).astype(acc)),
                dev=dev.astype(acc))


@functools.partial(jax.jit, static_argnames=_FAM_STATICS)
def _grad_chunk_kernel(X, y, wt, off, b0, fam_param, *, family, link,
                       precision):
    """lambda_max chunk: raw-weight ``(X'Wz, X'W1)`` at the null solution
    ``eta = b0 + offset``."""
    family = family.with_param(fam_param)
    dt = X.dtype
    acc = jnp.float64 if dt == jnp.float64 else jnp.float32
    valid = wt > 0.0
    eta = (b0 + off).astype(dt)
    mu = jnp.where(valid, link.inverse(eta), 1.0)
    w, z, _ = _work(y, wt, wt, off, eta, mu, family, link)
    u = design_colsum(X, w * z, accum_dtype=acc, precision=precision)
    v = design_colsum(X, w, accum_dtype=acc, precision=precision)
    return dict(u=u.astype(acc), v=v.astype(acc))


@functools.partial(jax.jit, static_argnames=_FAM_STATICS)
def _fisher_chunk_kernel(X, y, wt, off, beta, fam_param, *, family, link,
                         precision):
    """One IRLS data chunk at ``beta`` (ORIGINAL scale): raw-weight
    ``(X'WX, X'Wz, deviance)`` — the streaming twin of the resident path
    kernel's inner Gramian."""
    family = family.with_param(fam_param)
    dt = X.dtype
    acc = jnp.float64 if dt == jnp.float64 else jnp.float32
    valid = wt > 0.0
    eta = (design_matvec(X, beta.astype(dt)) + off).astype(dt)
    mu = jnp.where(valid, link.inverse(eta), 1.0).astype(dt)
    w, z, dev = _work(y, wt, wt, off, eta, mu, family, link)
    A, b = design_gramian(X, z, w, accum_dtype=acc, precision=precision)
    return dict(A=A.astype(acc), b=b.astype(acc), dev=dev.astype(acc))


@functools.partial(jax.jit, static_argnames=_FAM_STATICS)
def _dev_chunk_kernel(X, y, wt, off, beta, fam_param, *, family, link,
                      precision):
    """Deviance-only chunk at ``beta`` — the per-lambda reporting pass
    (O(n p) matvec, no Gramian)."""
    family = family.with_param(fam_param)
    dt = X.dtype
    acc = jnp.float64 if dt == jnp.float64 else jnp.float32
    valid = wt > 0.0
    eta = (design_matvec(X, beta.astype(dt)) + off).astype(dt)
    mu = jnp.where(valid, link.inverse(eta), 1.0).astype(dt)
    dev = jnp.sum(jnp.where(
        valid,
        jnp.nan_to_num(family.dev_resids(y, mu, wt),
                       nan=0.0, posinf=0.0, neginf=0.0), 0.0))
    return dict(dev=dev.astype(acc))


# -- host plumbing -----------------------------------------------------------


def _stream_pass(source, label, tracer, bucket, dtype, per_chunk):
    """Drive one chunked pass: materialize thunks, validate, bucket-pad to
    the fixed shape set, and fold ``per_chunk(X, y, w, off)`` host-f64
    partials left-to-right.  Returns ``(totals dict, chunks, rows)``."""
    import time as _time

    from ..models.streaming import _bucket_pad, _materialize

    totals: dict = {}
    chunks = rows = 0
    t0 = _time.perf_counter()
    if tracer is not None:
        tracer.pass_start(label, 0)
    for chunk in source():
        Xc, yc, wc, oc = _materialize(chunk)
        n = int(Xc.shape[0])
        if n == 0:
            continue
        rows += n
        chunks += 1
        Xc, yc, wc, oc = _bucket_pad(Xc, yc, wc, oc, bucket)
        Xc = Xc.astype(dtype)
        yc = np.asarray(yc, dtype)
        wc = (np.ones(Xc.shape[0], dtype) if wc is None
              else np.asarray(wc, dtype))
        oc = (np.zeros(Xc.shape[0], dtype) if oc is None
              else np.asarray(oc, dtype))
        part = per_chunk(Xc, yc, wc, oc)
        for k, v in part.items():
            v = np.asarray(v, np.float64)
            totals[k] = v if k not in totals else totals[k] + v
    if tracer is not None:
        tracer.pass_end(label, 0, chunks=chunks, rows=rows, bytes=0,
                        compute_s=_time.perf_counter() - t0)
    return totals, chunks, rows


def _grid_from(lam_max, penalty, n, p_pen):
    explicit = penalty.resolved_lambdas()
    if explicit is not None:
        return explicit
    lmr = penalty.min_ratio(n, p_pen)
    lg = np.log(max(lam_max, _TINY))
    return np.exp(np.linspace(lg, lg + np.log(lmr), penalty.grid_size()))


def _sd_from_moments(diagA, s1, pen, standardize, p):
    var_c = diagA - s1 ** 2
    if standardize:
        sdv = np.sqrt(np.maximum(var_c, 0.0))
        return np.where(pen & (sdv > _SD_FLOOR), sdv, 1.0)
    return np.ones(p)


def _prepare(penalty, xnames, has_intercept):
    from .penalty import ElasticNet

    if not isinstance(penalty, ElasticNet):
        raise TypeError(
            f"penalty must be an ElasticNet instance, got {type(penalty)!r}")
    xnames = tuple(xnames)
    icol = intercept_col(list(xnames), has_intercept)
    pfv = resolve_penalty_vector(penalty, list(xnames), has_intercept, icol)
    return xnames, icol, pfv


def _resolve_path_ckpt(source, checkpoint, resume):
    """Shared ``checkpoint=``/``resume=`` plumbing for the path drivers:
    ``(ckpt, resume_ck, state, fingerprint, source')`` via the streaming
    engine's resolver + first-chunk identity probe.  The probe only runs
    when durability is actually requested — the plain path is untouched."""
    from ..models.streaming import _resolve_resume, _source_first_chunk

    ckpt, resume_ck, state = _resolve_resume(checkpoint, resume, 1)
    src_fp = None
    if ckpt is not None or state is not None:
        src_fp, _, _, source = _source_first_chunk(source)
    return ckpt, resume_ck, state, src_fp, source


def _ckpt_str(state, key):
    return bytes(np.asarray(state[key])).decode()


def lm_path_streaming(source, *, penalty, xnames, yname="y",
                      has_intercept=None, verbose=False, retry=None,
                      checkpoint=None, resume=False,
                      trace=None, metrics=None, config=None):
    """Gaussian/identity lambda path from a chunk source in ONE data pass
    (module docstring).  ``source()`` yields ``(X, y, w, off)`` tuples or
    thunks, the ``models/streaming.py`` contract.

    ``retry=`` (a ``robust.RetryPolicy``) wraps the source so every chunk
    pass absorbs transient read failures in place, each pass under its own
    fresh budget (``robust/retry.py::retrying_source``).

    ``checkpoint=`` (path or ``robust.CheckpointManager``) makes the
    expensive part durable: the gaussian path streams data exactly ONCE
    (the Gramian accumulation — everything after is p x p work), so the
    lambda-path boundary to checkpoint at IS the end of that pass.
    ``resume=True`` (or ``resume=path``) restores the accumulated moments
    after fingerprint validation and re-runs the compiled path kernel
    without touching the data; with the same ``penalty=`` spec the resumed
    model is bit-for-bit the uninterrupted one (the kernel consumes only
    the checkpointed host-f64 totals)."""
    from ..config import DEFAULT, resolve_matmul_precision, x64_enabled

    if config is None:
        config = DEFAULT
    if retry is not None:
        from ..robust.retry import retrying_source
        source = retrying_source(source, retry)
    ckpt, resume_ck, state, src_fp, source = _resolve_path_ckpt(
        source, checkpoint, resume)
    xnames, icol, pfv = _prepare(penalty, xnames, has_intercept)
    p = len(xnames)
    dtype = np.float64 if x64_enabled() else np.float32
    tracer = _obs_trace.as_tracer(trace, verbose=verbose, metrics=metrics)
    mmp = resolve_matmul_precision(config, 1 << 20, p,
                                   jax.default_backend() == "tpu")
    bucket: dict = {}
    compiles = [0]
    engine = ["einsum"]

    def per_chunk(Xc, yc, wc, oc):
        from ..data.structured import StructuredDesign
        from ..models.streaming import _traced_call
        if isinstance(Xc, StructuredDesign):
            engine[0] = "structured"
        before = _stats_chunk_kernel._cache_size()
        out = _traced_call(_stats_chunk_kernel, tracer, "penalized_stats",
                           Xc, yc, wc, oc, engine=engine[0], precision=mmp)
        compiles[0] += _stats_chunk_kernel._cache_size() - before
        return out

    with _obs_trace.ambient(tracer):
        if tracer is not None:
            tracer.emit("fit_start", model="penalized_path_streaming",
                        family="gaussian", link="identity",
                        alpha=float(penalty.alpha))
        if state is not None:
            resume_ck.validate(state, kind="lm_path", fingerprint=src_fp, p=p)
            totals = {k: np.asarray(state[k], np.float64)
                      for k in ("A", "b", "s1", "yty", "wsum", "n_ok")}
            rows = int(state["rows"])
            engine[0] = _ckpt_str(state, "engine")
        else:
            totals, chunks, rows = _stream_pass(
                source, "penalized_gramian", tracer, bucket, dtype, per_chunk)
        if rows == 0:
            raise ValueError("chunk source produced no rows")
        wsum = float(totals["wsum"])
        if wsum <= 0:
            raise ValueError("weights sum to zero; nothing to fit")
        n_ok = int(totals["n_ok"])
        if ckpt is not None and state is None:
            ckpt.save(kind="lm_path", fingerprint=src_fp, p=p,
                      A=totals["A"], b=totals["b"], s1=totals["s1"],
                      yty=totals["yty"], n_ok=totals["n_ok"],
                      wsum=totals["wsum"], rows=rows,
                      engine=np.bytes_(engine[0].encode()))
        A = totals["A"] / wsum
        b = totals["b"] / wsum
        s1 = totals["s1"] / wsum
        yty = float(totals["yty"]) / wsum

        before = _gram_path_kernel._cache_size()
        explicit = penalty.resolved_lambdas()
        auto_grid = explicit is None
        n_lambda = penalty.grid_size()
        lmr = penalty.min_ratio(rows, p - (1 if icol is not None else 0))
        out = _gram_path_kernel(
            A.astype(dtype), b.astype(dtype), s1.astype(dtype),
            np.asarray(yty, dtype), np.asarray(wsum, dtype),
            (np.zeros(n_lambda, dtype) if auto_grid
             else explicit.astype(dtype)),
            np.asarray(lmr, dtype), np.asarray(penalty.alpha, dtype),
            pfv.astype(dtype), np.asarray(penalty.cd_tol, dtype),
            auto_grid=auto_grid, n_lambda=n_lambda,
            standardize=penalty.standardize, icol=icol,
            cd_max_sweeps=penalty.cd_max_sweeps, kkt_rounds=_KKT_ROUNDS,
            trace=tracer is not None)
        delta = _gram_path_kernel._cache_size() - before
        compiles[0] += delta
        if tracer is not None and delta:
            tracer.emit("compile", target="gram_path",
                        executables=int(delta), gramian_engine=engine[0])
        jax.effects_barrier()

        from ..families.families import resolve as _resolve
        fam, lnk = _resolve("gaussian", None)
        return assemble_path_model(
            out, penalty=penalty, fam=fam, lnk=lnk, xnames=xnames,
            yname=yname, n_obs=rows, n_ok=n_ok,
            has_intercept=bool(has_intercept), kind="lm", engine=engine[0],
            tracer=tracer, compiles=int(compiles[0]), has_offset=False)


def glm_path_streaming(source, *, family="binomial", link=None, penalty,
                       xnames, yname="y", has_intercept=None, verbose=False,
                       retry=None, checkpoint=None, resume=False,
                       trace=None, metrics=None, config=None):
    """General-family lambda path from a chunk source: host lambda/IRLS
    loops over a fixed set of compiled chunk-pass flavors plus the
    lambda-traced CD solve kernel (module docstring).  ``retry=`` wraps the
    source exactly as in :func:`lm_path_streaming` — every pass of the
    lambda/IRLS loops absorbs transient chunk failures in place.

    ``checkpoint=`` saves the path trajectory at every LAMBDA BOUNDARY —
    the natural durability grain: each grid point costs O(IRLS iterations)
    full data passes, and between grid points the whole state is tiny host
    vectors (active-set memory, warm-start beta, strong-rule gradient,
    accumulated per-lambda results).  ``resume=`` validates the source
    fingerprint plus family/link/alpha and continues the lambda loop from
    the first unfitted grid point; passes are deterministic given the
    source, so with the same ``penalty=`` spec the resumed path is
    bit-for-bit the uninterrupted one."""
    from ..config import DEFAULT, resolve_matmul_precision, x64_enabled
    from ..families.families import resolve as _resolve
    from ..models.streaming import _traced_call

    if config is None:
        config = DEFAULT
    fam, lnk = _resolve(family, link)
    if fam.name == "gaussian" and lnk.name == "identity":
        return lm_path_streaming(
            source, penalty=penalty, xnames=xnames, yname=yname,
            has_intercept=has_intercept, verbose=verbose, retry=retry,
            checkpoint=checkpoint, resume=resume,
            trace=trace, metrics=metrics, config=config)
    if retry is not None:
        from ..robust.retry import retrying_source
        source = retrying_source(source, retry)
    ckpt, resume_ck, state, src_fp, source = _resolve_path_ckpt(
        source, checkpoint, resume)
    xnames, icol, pfv = _prepare(penalty, xnames, has_intercept)
    p = len(xnames)
    dtype = np.float64 if x64_enabled() else np.float32
    tracer = _obs_trace.as_tracer(trace, verbose=verbose, metrics=metrics)
    mmp = resolve_matmul_precision(config, 1 << 20, p,
                                   jax.default_backend() == "tpu")
    fam_param = fam.param_operand(dtype)
    bucket: dict = {}
    compiles = [0]
    engine = ["einsum"]
    fam_kw = dict(family=fam, link=lnk, precision=mmp)

    def counted(kernel, target, *args, **kw):
        from ..data.structured import StructuredDesign
        if args and isinstance(args[0], StructuredDesign):
            engine[0] = "structured"
        before = kernel._cache_size()
        out = _traced_call(kernel, tracer, target, *args,
                           engine=engine[0], **kw)
        compiles[0] += kernel._cache_size() - before
        return out

    with _obs_trace.ambient(tracer):
        if tracer is not None:
            tracer.emit("fit_start", model="penalized_path_streaming",
                        family=fam.name, link=lnk.name,
                        alpha=float(penalty.alpha))
        pen = pfv > 0.0
        if state is not None:
            # resume at a lambda boundary: validate identity, restore the
            # tiny host trajectory, skip the stats/null/grad passes
            resume_ck.validate(state, kind="glm_path",
                               fingerprint=src_fp, p=p)
            if (_ckpt_str(state, "family") != fam.name
                    or _ckpt_str(state, "link") != lnk.name
                    or float(state["alpha"]) != float(penalty.alpha)):
                raise ValueError(
                    f"checkpoint {resume_ck.path!r} was written by a "
                    f"{_ckpt_str(state, 'family')}/{_ckpt_str(state, 'link')}"
                    f" path at alpha={float(state['alpha'])}; resuming a "
                    f"{fam.name}/{lnk.name} path at "
                    f"alpha={float(penalty.alpha)} from it would corrupt "
                    f"the trajectory — delete the checkpoint (or drop "
                    f"resume=) to start over")
            engine[0] = _ckpt_str(state, "engine")
            rows = int(state["rows"])
            n_ok = int(state["n_ok"])
            wsum = float(state["wsum"])
            sd = np.asarray(state["sd"], np.float64)
            isd = 1.0 / sd
            b0 = float(state["b0"])
            null_dev = float(state["null_dev"])
            lams = np.asarray(state["lams"], np.float64)
            g = np.asarray(state["g"], np.float64)
            lam_prev = float(state["lam_prev"])
            ever = np.asarray(state["ever"], bool).copy()
            beta_std = np.asarray(state["beta_std"], np.float64).copy()
            k0 = int(state["k"])
            betas = list(np.asarray(state["betas"], np.float64))
            dfs = [int(v) for v in state["dfs"]]
            devs = [float(v) for v in state["devs"]]
            its = [int(v) for v in state["its"]]
            sws = [int(v) for v in state["sws"]]
            convs = [bool(v) for v in state["convs"]]
            kkts = [bool(v) for v in state["kkts"]]
        else:
            # pass 1: standardization stats (first/second weighted moments)
            totals, chunks, rows = _stream_pass(
                source, "penalized_stats", tracer, bucket, dtype,
                lambda Xc, yc, wc, oc: counted(
                    _stats_chunk_kernel, "penalized_stats", Xc, yc, wc, oc,
                    precision=mmp))
            if rows == 0:
                raise ValueError("chunk source produced no rows")
            wsum = float(totals["wsum"])
            if wsum <= 0:
                raise ValueError("weights sum to zero; nothing to fit")
            n_ok = int(totals["n_ok"])
            sd = _sd_from_moments(np.diag(totals["A"]) / wsum,
                                  totals["s1"] / wsum, pen,
                                  penalty.standardize, p)
            isd = 1.0 / sd

            # pass 2..k: intercept-only null IRLS (scalar chunk partials)
            def null_pass(b0, first):
                tot, _, _ = _stream_pass(
                    source, "penalized_null", tracer, bucket, dtype,
                    lambda Xc, yc, wc, oc: counted(
                        _null_chunk_kernel,
                        "penalized_null_first" if first else "penalized_null",
                        yc, wc, oc, np.asarray(b0, dtype), fam_param,
                        first=first, **fam_kw))
                return (float(tot["sw"]), float(tot["swz"]),
                        float(tot["dev"]))

            b0 = 0.0
            if icol is not None:
                sw, swz, dev_prev = null_pass(0.0, True)
                for it in range(_NULL_MAX_ITER):
                    b0 = swz / max(sw, _TINY)
                    sw, swz, dev = null_pass(b0, False)
                    if abs(dev - dev_prev) <= _NULL_TOL * (abs(dev) + 0.1):
                        dev_prev = dev
                        break
                    dev_prev = dev
                null_dev = dev_prev
            else:
                _, _, null_dev = null_pass(0.0, False)

            # lambda_max gradient at the null solution
            gtot, _, _ = _stream_pass(
                source, "penalized_grad", tracer, bucket, dtype,
                lambda Xc, yc, wc, oc: counted(
                    _grad_chunk_kernel, "penalized_grad", Xc, yc, wc, oc,
                    np.asarray(b0, dtype), fam_param, **fam_kw))
            g = (gtot["u"] - b0 * gtot["v"]) * isd / wsum
            al = max(float(penalty.alpha), _ALPHA_FLOOR)
            lam_max = float(np.max(np.where(
                pen, np.abs(g) / (al * np.maximum(pfv, _TINY)), 0.0)))
            lam_max = max(lam_max, _TINY)
            lams = _grid_from(lam_max, penalty, rows,
                              p - (1 if icol is not None else 0))

            ever = np.zeros(p, bool)
            beta_std = np.zeros(p)
            if icol is not None:
                beta_std[icol] = b0
            lam_prev = lam_max
            k0 = 0
            betas, dfs, devs = [], [], []
            its, sws, convs, kkts = [], [], [], []

        # the path: host lambda loop, host IRLS loop, compiled passes
        alpha = float(penalty.alpha)
        free = ~pen

        def fisher(beta_orig):
            tot, _, _ = _stream_pass(
                source, "penalized_fisher", tracer, bucket, dtype,
                lambda Xc, yc, wc, oc: counted(
                    _fisher_chunk_kernel, "penalized_fisher", Xc, yc, wc,
                    oc, beta_orig.astype(dtype), fam_param, **fam_kw))
            As = (tot["A"] / wsum) * isd[:, None] * isd[None, :]
            bs = (tot["b"] / wsum) * isd
            return As, bs, float(tot["dev"])

        for k in range(k0, len(lams)):
            lam = float(lams[k])
            strong = pen & (np.abs(g)
                            >= alpha * pfv * (2.0 * lam - lam_prev) - 1e-12)
            mask = free | ever | strong
            go, rounds = True, 0
            it_total = sweeps_total = 0
            crit = np.inf
            while go and rounds < _KKT_ROUNDS:
                it = 0
                while it == 0 or (crit > penalty.tol
                                  and it < penalty.max_iter):
                    As, bs, _ = fisher(beta_std * isd)
                    sol = counted(
                        _cd_solve_kernel, "penalized_cd",
                        As.astype(dtype), bs.astype(dtype),
                        beta_std.astype(dtype), np.asarray(lam, dtype),
                        np.asarray(alpha, dtype), pfv.astype(dtype),
                        mask, np.asarray(penalty.cd_tol, dtype),
                        cd_max_sweeps=penalty.cd_max_sweeps)
                    beta_std = np.asarray(sol["beta"], np.float64)
                    crit = float(sol["crit"])
                    sweeps_total += int(sol["sweeps"])
                    it += 1
                it_total += it
                g = np.asarray(sol["g"], np.float64)
                viol = pen & ~mask & (np.abs(g)
                                      > alpha * pfv * lam * (1 + 1e-4)
                                      + 1e-9)
                mask |= viol
                go = bool(viol.any())
                rounds += 1
            beta_orig = beta_std * isd
            dtot, _, _ = _stream_pass(
                source, "penalized_dev", tracer, bucket, dtype,
                lambda Xc, yc, wc, oc: counted(
                    _dev_chunk_kernel, "penalized_dev", Xc, yc, wc, oc,
                    beta_orig.astype(dtype), fam_param, **fam_kw))
            dev = float(dtot["dev"])
            nz = pen & (np.abs(beta_std) > 0.0)
            ever |= nz
            lam_prev = lam
            betas.append(beta_orig)
            dfs.append(int(nz.sum()))
            devs.append(dev)
            its.append(it_total)
            sws.append(sweeps_total)
            convs.append(crit <= penalty.tol)
            kkts.append(not go)
            if tracer is not None:
                tracer.emit("path_point", index=k, lambda_=lam,
                            df=int(nz.sum()), deviance=dev, iters=it_total,
                            sweeps=sweeps_total)
                tracer.emit("solve", target="path_lambda", index=k,
                            iters=it_total)
            if ckpt is not None:
                ckpt.save(kind="glm_path", fingerprint=src_fp, p=p,
                          family=np.bytes_(fam.name.encode()),
                          link=np.bytes_(lnk.name.encode()),
                          alpha=float(penalty.alpha),
                          engine=np.bytes_(engine[0].encode()),
                          k=k + 1, rows=rows, n_ok=n_ok, wsum=wsum,
                          sd=sd, b0=b0, null_dev=null_dev,
                          lams=np.asarray(lams), g=g, lam_prev=lam_prev,
                          ever=ever, beta_std=beta_std,
                          betas=np.asarray(betas), dfs=dfs, devs=devs,
                          its=its, sws=sws, convs=convs, kkts=kkts)

        out = dict(lambdas=np.asarray(lams), beta=np.asarray(betas),
                   dev=np.asarray(devs), null_dev=null_dev,
                   df=np.asarray(dfs), conv=np.asarray(convs),
                   kkt_ok=np.asarray(kkts), iters=np.asarray(its),
                   sweeps=np.asarray(sws))
        return assemble_path_model(
            out, penalty=penalty, fam=fam, lnk=lnk, xnames=xnames,
            yname=yname, n_obs=rows, n_ok=n_ok,
            has_intercept=bool(has_intercept), kind="glm", engine=engine[0],
            tracer=tracer, compiles=int(compiles[0]), has_offset=False)
