"""Penalized GLMs: elastic-net lambda paths compiled as one executable.

The subsystem behind ``penalty=ElasticNet(...)`` on the ``lm``/``glm``
and ``*_from_csv`` front-ends (ROADMAP item 2; glmnet is the behavioral
oracle — PARITY.md r11 documents the correspondence and tolerances).

  * ``penalty.py``  — the :class:`ElasticNet` spec (alpha mix, lambda
    grid request, standardization, penalty factors, solver tolerances).
  * ``path.py``     — the compiled kernels: one-executable lax.scan
    lambda path with traced lambda, strong-rule screening + KKT
    verification, warm starts; Gramian-level gaussian path; the
    single-solve kernel the streaming driver reuses.
  * ``stream.py``   — out-of-core paths: penalization operates on the
    ACCUMULATED X'WX / X'Wz, so the chunked streaming engine's passes
    feed the same solvers.
  * ``model.py``    — :class:`PathModel` (coefficients over lambda, df,
    deviance explained) and ``select()`` back to ordinary models.
"""

from .model import PathModel
from .path import fit_path
from .penalty import ElasticNet

__all__ = ["ElasticNet", "PathModel", "fit_path"]
