"""Elastic-net penalty specification.

``ElasticNet`` is the user-facing ``penalty=`` argument of ``lm``/``glm``
and the ``*_from_csv`` front-ends (api.py): a frozen, hashable record of
the penalty geometry (``alpha`` blends l1 and l2) and the lambda-path
request (an explicit grid, or an automatic lambda_max-anchored log grid).
The solver semantics follow glmnet (Friedman/Hastie/Tibshirani), the
behavioral oracle named in ROADMAP item 2 — see PARITY.md r11 for the
exact correspondence (weight normalization, standardization moments,
intercept handling) and its documented tolerances.

The objective, for a fitted mean eta = X beta and prior weights w
rescaled to sum n (glmnet's internal rescaling):

    (1/n) * sum_i w_i * nll_i(y_i, eta_i)
      + lambda * sum_j pf_j * (alpha * |beta_j| + (1-alpha)/2 * beta_j^2)

with nll the family's unit deviance / 2 (gaussian: (y-eta)^2 / 2).  The
intercept (and any ``penalty_factor`` zero) is never penalized.
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["ElasticNet"]


@dataclasses.dataclass(frozen=True)
class ElasticNet:
    """Elastic-net penalty over a lambda path.

    Attributes:
      alpha: l1/l2 mix in [0, 1] — 1 is the lasso, 0 is ridge (glmnet's
        ``alpha``).
      lambdas: explicit penalty grid (any order; fitted descending).  None
        (default) builds the glmnet-style automatic grid: ``n_lambda``
        log-spaced points from the data-derived lambda_max (the smallest
        lambda with every penalized coefficient zero) down to
        ``lambda_min_ratio * lambda_max``.
      n_lambda: automatic-grid length (glmnet ``nlambda``, default 100).
      lambda_min_ratio: automatic-grid floor ratio; None picks glmnet's
        default (1e-4 when n > p, else 1e-2).
      standardize: scale each penalized column by its weighted standard
        deviation (moments about the weighted mean, 1/n denominator)
        before penalizing; coefficients are always returned on the
        ORIGINAL scale.  glmnet's ``standardize=TRUE`` default.
      penalty_factor: optional per-column multipliers aligned to xnames
        (glmnet ``penalty.factor``); 0 exempts a column.  The intercept
        is forced to 0 regardless.
      max_iter: IRLS (outer quadratic-approximation) iterations per
        lambda; warm starts along the path typically need 1-3.
      tol: IRLS convergence threshold on the weighted coefficient change
        ``max_j A_jj (dbeta_j)^2`` (glmnet's outer criterion).
      cd_tol: coordinate-descent sweep threshold, same functional
        (glmnet ``thresh``).
      cd_max_sweeps: CD sweep cap per inner solve.
    """

    alpha: float = 1.0
    lambdas: tuple | None = None
    n_lambda: int = 100
    lambda_min_ratio: float | None = None
    standardize: bool = True
    penalty_factor: tuple | None = None
    max_iter: int = 25
    tol: float = 1e-7
    cd_tol: float = 1e-7
    cd_max_sweeps: int = 1000

    def __post_init__(self):
        a = float(self.alpha)
        if not np.isfinite(a) or not 0.0 <= a <= 1.0:
            raise ValueError(f"alpha must be in [0, 1], got {self.alpha!r}")
        object.__setattr__(self, "alpha", a)
        if self.lambdas is not None:
            lams = tuple(float(l) for l in np.asarray(self.lambdas).ravel())
            if not lams:
                raise ValueError("lambdas must be non-empty when given")
            if any(not np.isfinite(l) or l < 0.0 for l in lams):
                raise ValueError(
                    f"lambdas must be finite and >= 0, got {self.lambdas!r}")
            # fitted largest-first so warm starts walk a shrinking penalty;
            # PathModel keeps this descending order
            object.__setattr__(self, "lambdas",
                               tuple(sorted(set(lams), reverse=True)))
        if int(self.n_lambda) < 1:
            raise ValueError(f"n_lambda must be >= 1, got {self.n_lambda!r}")
        object.__setattr__(self, "n_lambda", int(self.n_lambda))
        if self.lambda_min_ratio is not None:
            r = float(self.lambda_min_ratio)
            if not 0.0 < r < 1.0:
                raise ValueError(
                    f"lambda_min_ratio must be in (0, 1), got {r!r}")
        if self.penalty_factor is not None:
            pf = tuple(float(v) for v in np.asarray(self.penalty_factor).ravel())
            if any(not np.isfinite(v) or v < 0.0 for v in pf):
                raise ValueError("penalty_factor entries must be finite and >= 0")
            object.__setattr__(self, "penalty_factor", pf)

    def resolved_lambdas(self) -> np.ndarray | None:
        """The explicit descending grid, or None for the automatic one."""
        if self.lambdas is None:
            return None
        return np.asarray(self.lambdas, np.float64)

    def grid_size(self) -> int:
        return len(self.lambdas) if self.lambdas is not None else self.n_lambda

    def min_ratio(self, n: int, p: int) -> float:
        if self.lambda_min_ratio is not None:
            return float(self.lambda_min_ratio)
        return 1e-4 if n > p else 1e-2
