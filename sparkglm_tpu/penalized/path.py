"""Elastic-net lambda-path kernels: the whole path as ONE executable.

The subsystem's fitting core (ROADMAP item 2; glmnet as the behavioral
oracle, PAPERS.md arXiv 1902.06391 for IRLS-with-l1 convergence).  Three
compiled kernels:

  * :func:`_glm_path_kernel` — the general resident path.  A single jit
    holds the standardization-stats Gramian, the intercept-only null IRLS
    (O(n) per iteration — no p x p work), the data-derived lambda_max and
    automatic log grid, and a ``lax.scan`` over the DESCENDING lambda grid
    with lambda as a traced scalar.  Each scan step warm-starts from the
    previous solution, screens with the sequential strong rule, runs IRLS
    (working response -> weighted Gramian -> coordinate descent on the
    standardized normal equations), and re-checks the KKT conditions of
    screened-out coordinates, re-solving with violators admitted (bounded
    rounds).  A 100-point path therefore costs ~100 extra solves and ZERO
    extra compiles — the one-executable contract tests assert the jit
    cache-size delta, as ``data/pipeline.py`` does for streaming chunks.
  * :func:`_gram_path_kernel` — the gaussian/identity path on an already
    ACCUMULATED Gramian ``(X'WX, X'Wz)``.  The quadratic objective never
    re-weights, so the data is touched once (resident: one stats kernel;
    streaming: one chunk-accumulation pass) and the whole path is p x p
    work.  This is what makes out-of-core lm paths one-data-pass.
  * :func:`_cd_solve_kernel` — one standardized elastic-net solve with
    lambda traced, the inner step of the streaming GLM path driver
    (``penalized/stream.py``), which must interleave host-side chunk
    passes with device solves and so cannot fuse the scan.

Solver semantics (PARITY.md r11): prior weights are normalized to sum 1,
making every Gramian an observation-average — the objective is glmnet's

    sum_i (w_i / sum w) nll_i + lambda sum_j pf_j (alpha |b_j|
                                                   + (1 - alpha)/2 b_j^2)

Columns are standardized by the weighted standard deviation about the
weighted mean (1/n denominator) but NOT centered: with an unpenalized
intercept the centered and uncentered problems have identical penalized
coefficients (the intercept absorbs the shift), and skipping centering
keeps StructuredDesign factor blocks one-gather sparse.  Coefficients
return on the ORIGINAL scale.  Coordinate updates are the classic
covariance-form soft-threshold:

    b_j <- S(g_j, lambda alpha pf_j) / (A_jj + lambda (1-alpha) pf_j),
    g_j = b_s[j] - (A_s b)_j + A_s[j,j] b_j

with ``A_s = D (X'WX) D``, ``b_s = D X'Wz``, ``D = diag(1/sd)`` (all on
normalized weights).  IRLS outer convergence is glmnet's
``max_j A_jj (db_j)^2 < tol``; the CD sweeps share the same functional.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import trace as _obs_trace
from ..ops.factor_gramian import design_colsum, design_gramian, design_matvec

__all__ = ["fit_path"]

_TINY = 1e-30
_NULL_MAX_ITER = 50
_NULL_TOL = 1e-9          # relative ddev; the null fit is O(n) per iteration
_KKT_ROUNDS = 3           # violator-admission re-solves per lambda
_ALPHA_FLOOR = 1e-3       # glmnet's lambda_max guard as alpha -> 0 (ridge)
_SD_FLOOR = 1e-10         # below this a column is constant: sd forced to 1


def _soft(x, t):
    """Soft-threshold S(x, t) = sign(x) max(|x| - t, 0)."""
    return jnp.sign(x) * jnp.maximum(jnp.abs(x) - t, 0.0)


def _work(y, wt, wp, off, eta, mu, family, link):
    """One IRLS re-weighting: working weights/response on the NORMALIZED
    prior weights ``wp`` (they feed the averaged Gramian), deviance on the
    RAW weights ``wt`` (it is reported next to unpenalized fits).  Per-row
    sanitization mirrors ``ops/factor_gramian.structured_fisher_pass``."""
    valid = wt > 0.0
    g = link.deriv(mu)
    var = family.variance(mu)
    w_raw = wp / jnp.maximum(var * g * g, _TINY)
    w = jnp.where(valid,
                  jnp.nan_to_num(w_raw, nan=0.0, posinf=0.0, neginf=0.0), 0.0)
    z_raw = eta - off + (y - mu) * g
    z = jnp.where(valid,
                  jnp.nan_to_num(z_raw, nan=0.0, posinf=0.0, neginf=0.0), 0.0)
    dev = jnp.sum(jnp.where(
        valid,
        jnp.nan_to_num(family.dev_resids(y, mu, wt),
                       nan=0.0, posinf=0.0, neginf=0.0), 0.0))
    return w, z, dev


def _cd_solve(As, bs, beta0, lam, alpha, pf, mask, cd_tol, cd_max_sweeps):
    """Cyclic coordinate descent on the standardized normal equations,
    restricted to ``mask`` (screened-out coordinates stay exactly 0).
    Returns ``(beta, sweeps, last_delta)``."""
    acc = As.dtype
    diag = jnp.diag(As)
    l1 = (lam * alpha * pf).astype(acc)
    denom = jnp.maximum(diag + lam * (1.0 - alpha) * pf, _TINY).astype(acc)
    beta_start = jnp.where(mask, beta0, 0.0).astype(acc)
    p = bs.shape[0]

    def coord(j, bt):
        gj = bs[j] - As[j] @ bt + diag[j] * bt[j]
        bj = _soft(gj, l1[j]) / denom[j]
        return bt.at[j].set(jnp.where(mask[j], bj, bt[j]))

    def sweep(s):
        bnew = jax.lax.fori_loop(0, p, coord, s["beta"])
        d = jnp.max(diag * (bnew - s["beta"]) ** 2)
        return dict(beta=bnew, delta=d, sweeps=s["sweeps"] + 1)

    def cond(s):
        return (s["sweeps"] == 0) | ((s["delta"] > cd_tol)
                                     & (s["sweeps"] < cd_max_sweeps))

    out = jax.lax.while_loop(cond, sweep, dict(
        beta=beta_start, delta=jnp.asarray(jnp.inf, acc),
        sweeps=jnp.zeros((), jnp.int32)))
    return out["beta"], out["sweeps"], out["delta"]


def _null_model(y, wt, wp, off, valid, family, link, icol, acc):
    """Intercept-only IRLS (scalar normal equation, O(n)/iteration).
    Returns ``(b0, null_dev, w, z)`` with the working vectors at the null
    solution — the lambda_max gradient needs them."""
    mu0 = jnp.where(valid, family.init_mu(y, jnp.maximum(wt, _TINY)), 1.0)
    eta0 = link.link(mu0)
    w0, z0, dev0 = _work(y, wt, wp, off, eta0, mu0, family, link)
    if icol is None:
        # no intercept: the null model is eta = offset, beta = 0
        mu = jnp.where(valid, link.inverse(off), 1.0)
        w, z, dev = _work(y, wt, wp, off, off, mu, family, link)
        return jnp.zeros((), acc), dev.astype(acc), w, z

    def body(s):
        b0 = jnp.sum(s["w"] * s["z"]) / jnp.maximum(jnp.sum(s["w"]), _TINY)
        eta = b0 + off
        mu = jnp.where(valid, link.inverse(eta), 1.0)
        w, z, dev = _work(y, wt, wp, off, eta, mu, family, link)
        return dict(b0=b0.astype(acc), w=w, z=z, dev=dev.astype(acc),
                    ddev=jnp.abs(dev - s["dev"]).astype(acc),
                    it=s["it"] + 1)

    def cond(s):
        return (s["it"] == 0) | (
            (s["ddev"] > _NULL_TOL * (jnp.abs(s["dev"]) + 0.1))
            & (s["it"] < _NULL_MAX_ITER))

    s = jax.lax.while_loop(cond, body, dict(
        b0=jnp.zeros((), acc), w=w0, z=z0, dev=dev0.astype(acc),
        ddev=jnp.asarray(jnp.inf, acc), it=jnp.zeros((), jnp.int32)))
    return s["b0"], s["dev"], s["w"], s["z"]


def _emit_path_point(k, lam, df, dev, iters, sweeps) -> None:
    """``jax.debug.callback`` target: one ``path_point`` + one ``solve``
    event per lambda, routed through the ambient tracer (obs/trace.py)."""
    tr = _obs_trace.current_tracer()
    if tr is not None:
        tr.emit("path_point", index=int(k), lambda_=float(lam), df=int(df),
                deviance=float(dev), iters=int(iters), sweeps=int(sweeps))
        tr.emit("solve", target="path_lambda", index=int(k),
                iters=int(iters))


def _build_grid(lam_max, lambdas, lmr, n_lambda, auto_grid, acc):
    if auto_grid:
        lg = jnp.log(lam_max)
        return jnp.exp(jnp.linspace(lg, lg + jnp.log(lmr),
                                    n_lambda)).astype(acc)
    return lambdas.astype(acc)


_GLM_STATICS = ("family", "link", "auto_grid", "n_lambda", "standardize",
                "icol", "max_iter", "cd_max_sweeps", "kkt_rounds",
                "precision", "trace")


def _glm_path_core(X, y, wt, off, lambdas, lmr, alpha, pf, tol, cd_tol,
                   fam_param, *, family, link, auto_grid, n_lambda,
                   standardize, icol, max_iter, cd_max_sweeps,
                   kkt_rounds, precision, trace):
    """The whole GLM lambda-path (module docstring) — undecorated so the
    fleet path kernel (fleet/path.py) can map it over a stacked model
    axis; :func:`_glm_path_kernel` is the jitted solo entry."""
    family = family.with_param(fam_param)
    dt = X.dtype
    acc = jnp.float64 if dt == jnp.float64 else jnp.float32
    n, p = X.shape
    wt = wt.astype(dt)
    y = y.astype(dt)
    off = off.astype(dt)
    valid = wt > 0.0
    wp = (wt / jnp.sum(wt.astype(acc)).astype(dt))
    pen = pf > 0.0
    alpha = alpha.astype(acc)
    pf = pf.astype(acc)

    # standardization stats: one averaged Gramian gives both first and
    # second weighted moments of every column
    one = jnp.ones((n,), dt)
    A1, s1 = design_gramian(X, one, wp, accum_dtype=acc, precision=precision)
    var_c = jnp.diag(A1.astype(acc)) - s1.astype(acc) ** 2
    if standardize:
        sdv = jnp.sqrt(jnp.maximum(var_c, 0.0))
        sd = jnp.where(pen & (sdv > _SD_FLOOR), sdv, 1.0)
    else:
        sd = jnp.ones((p,), acc)
    isd = (1.0 / sd).astype(acc)

    b0, null_dev, w_n, z_n = _null_model(y, wt, wp, off, valid, family,
                                         link, icol, acc)

    # lambda_max: the standardized null-model gradient.  X'W(z - b0) with
    # sum-1 weights needs no /n; b0 folds in through X'W1.
    u = design_colsum(X, w_n * z_n, accum_dtype=acc, precision=precision)
    v = design_colsum(X, w_n, accum_dtype=acc, precision=precision)
    g0 = (u - b0 * v) * isd
    al = jnp.maximum(alpha, _ALPHA_FLOOR)
    lam_max = jnp.max(jnp.where(
        pen, jnp.abs(g0) / (al * jnp.maximum(pf, _TINY)), 0.0))
    lam_max = jnp.maximum(lam_max, _TINY)
    lams = _build_grid(lam_max, lambdas, lmr.astype(acc), n_lambda,
                       auto_grid, acc)

    beta_init = jnp.zeros((p,), acc)
    if icol is not None:
        beta_init = beta_init.at[icol].set(b0)  # sd[icol] is 1 (unpenalized)
    free = ~pen

    def irls_cond(s):
        return (s["it"] == 0) | ((s["crit"] > tol) & (s["it"] < max_iter))

    def step(carry, xs):
        lam, k = xs
        lam = lam.astype(acc)
        # sequential strong rule off the previous solution's gradient
        strong = pen & (jnp.abs(carry["g"])
                        >= alpha * pf * (2.0 * lam - carry["lam_prev"])
                        - 1e-12)
        mask0 = free | carry["ever"] | strong

        def irls(beta, mask):
            def ib(s):
                eta = (design_matvec(X, (s["beta"] * isd).astype(dt))
                       + off).astype(dt)
                mu = jnp.where(valid, link.inverse(eta), 1.0).astype(dt)
                w, z, dev = _work(y, wt, wp, off, eta, mu, family, link)
                A, b = design_gramian(X, z, w, accum_dtype=acc,
                                      precision=precision)
                As = A.astype(acc) * isd[:, None] * isd[None, :]
                bs = b.astype(acc) * isd
                bnew, sweeps, _ = _cd_solve(As, bs, s["beta"], lam, alpha,
                                            pf, mask, cd_tol, cd_max_sweeps)
                crit = jnp.max(jnp.diag(As) * (bnew - s["beta"]) ** 2)
                return dict(beta=bnew, As=As, bs=bs, dev=dev.astype(acc),
                            crit=crit.astype(acc), it=s["it"] + 1,
                            sweeps=s["sweeps"] + sweeps)
            return jax.lax.while_loop(irls_cond, ib, dict(
                beta=beta, As=jnp.zeros((p, p), acc),
                bs=jnp.zeros((p,), acc), dev=jnp.zeros((), acc),
                crit=jnp.asarray(jnp.inf, acc),
                it=jnp.zeros((), jnp.int32),
                sweeps=jnp.zeros((), jnp.int32)))

        def kkt_body(ks):
            r = irls(ks["beta"], ks["mask"])
            g = r["bs"] - r["As"] @ r["beta"]
            viol = pen & ~ks["mask"] & (
                jnp.abs(g) > alpha * pf * lam * (1.0 + 1e-4) + 1e-9)
            return dict(beta=r["beta"], mask=ks["mask"] | viol, g=g,
                        it=ks["it"] + r["it"],
                        sweeps=ks["sweeps"] + r["sweeps"],
                        crit=r["crit"], go=jnp.any(viol),
                        rounds=ks["rounds"] + 1)

        def kkt_cond(ks):
            return ks["go"] & (ks["rounds"] < kkt_rounds)

        ks = jax.lax.while_loop(kkt_cond, kkt_body, dict(
            beta=carry["beta"], mask=mask0, g=jnp.zeros((p,), acc),
            it=jnp.zeros((), jnp.int32), sweeps=jnp.zeros((), jnp.int32),
            crit=jnp.asarray(jnp.inf, acc), go=jnp.asarray(True),
            rounds=jnp.zeros((), jnp.int32)))
        beta = ks["beta"]
        # reported deviance, exactly at the returned solution
        eta = (design_matvec(X, (beta * isd).astype(dt)) + off).astype(dt)
        mu = jnp.where(valid, link.inverse(eta), 1.0).astype(dt)
        dev = jnp.sum(jnp.where(
            valid,
            jnp.nan_to_num(family.dev_resids(y, mu, wt),
                           nan=0.0, posinf=0.0, neginf=0.0),
            0.0)).astype(acc)
        nz = pen & (jnp.abs(beta) > 0.0)
        df = jnp.sum(nz).astype(jnp.int32)
        if trace:
            jax.debug.callback(_emit_path_point, k, lam, df, dev, ks["it"],
                               ks["sweeps"], ordered=True)
        new_carry = dict(beta=beta, ever=carry["ever"] | nz, g=ks["g"],
                         lam_prev=lam)
        ys = dict(beta=(beta * isd), df=df, dev=dev, iters=ks["it"],
                  sweeps=ks["sweeps"], conv=(ks["crit"] <= tol),
                  kkt_ok=~ks["go"])
        return new_carry, ys

    carry0 = dict(beta=beta_init, ever=jnp.zeros((p,), bool), g=g0,
                  lam_prev=lam_max)
    _, ys = jax.lax.scan(step, carry0,
                         (lams, jnp.arange(lams.shape[0], dtype=jnp.int32)))
    return dict(lambdas=lams, null_dev=null_dev, b0=b0, sd=sd, **ys)


@functools.partial(jax.jit, static_argnames=_GLM_STATICS)
def _glm_path_kernel(X, y, wt, off, lambdas, lmr, alpha, pf, tol, cd_tol,
                     fam_param, *, family, link, auto_grid, n_lambda,
                     standardize, icol, max_iter, cd_max_sweeps,
                     kkt_rounds, precision, trace):
    """The whole GLM lambda-path in one executable (module docstring)."""
    return _glm_path_core(
        X, y, wt, off, lambdas, lmr, alpha, pf, tol, cd_tol, fam_param,
        family=family, link=link, auto_grid=auto_grid, n_lambda=n_lambda,
        standardize=standardize, icol=icol, max_iter=max_iter,
        cd_max_sweeps=cd_max_sweeps, kkt_rounds=kkt_rounds,
        precision=precision, trace=trace)


_GRAM_STATICS = ("auto_grid", "n_lambda", "standardize", "icol",
                 "cd_max_sweeps", "kkt_rounds", "trace")


def _gram_path_core(A, b, s1, yty, wsum, lambdas, lmr, alpha, pf, cd_tol,
                    *, auto_grid, n_lambda, standardize, icol,
                    cd_max_sweeps, kkt_rounds, trace):
    """Gaussian/identity lambda-path from an ACCUMULATED weighted Gramian.

    ``A = X'WX``, ``b = X'Wz``, ``s1 = X'W1``, ``yty = z'Wz`` with
    W = diag(w / sum w) and ``z = y - offset``; ``wsum`` restores the
    RAW-weight deviance scale for reporting.  The quadratic objective
    needs no re-weighting, so the path never touches the data again —
    the enabling property for one-data-pass out-of-core lm paths."""
    acc = A.dtype
    p = b.shape[0]
    pen = pf > 0.0
    alpha = alpha.astype(acc)
    pf = pf.astype(acc)
    var_c = jnp.diag(A) - s1 ** 2
    if standardize:
        sdv = jnp.sqrt(jnp.maximum(var_c, 0.0))
        sd = jnp.where(pen & (sdv > _SD_FLOOR), sdv, 1.0)
    else:
        sd = jnp.ones((p,), acc)
    isd = (1.0 / sd).astype(acc)
    As = A * isd[:, None] * isd[None, :]
    bs = b * isd

    if icol is not None:
        # intercept-only WLS: one scalar normal equation
        b0 = b[icol] / jnp.maximum(A[icol, icol], _TINY)
        null_rss = jnp.maximum(yty - b0 * b0 * A[icol, icol], 0.0)
    else:
        b0 = jnp.zeros((), acc)
        null_rss = yty
    beta_init = jnp.zeros((p,), acc)
    if icol is not None:
        beta_init = beta_init.at[icol].set(b0)
    g0 = bs - As @ beta_init
    al = jnp.maximum(alpha, _ALPHA_FLOOR)
    lam_max = jnp.max(jnp.where(
        pen, jnp.abs(g0) / (al * jnp.maximum(pf, _TINY)), 0.0))
    lam_max = jnp.maximum(lam_max, _TINY)
    lams = _build_grid(lam_max, lambdas, lmr.astype(acc), n_lambda,
                       auto_grid, acc)
    free = ~pen

    def step(carry, xs):
        lam, k = xs
        lam = lam.astype(acc)
        strong = pen & (jnp.abs(carry["g"])
                        >= alpha * pf * (2.0 * lam - carry["lam_prev"])
                        - 1e-12)
        mask0 = free | carry["ever"] | strong

        def kkt_body(ks):
            beta, sweeps, delta = _cd_solve(As, bs, ks["beta"], lam, alpha,
                                            pf, ks["mask"], cd_tol,
                                            cd_max_sweeps)
            g = bs - As @ beta
            viol = pen & ~ks["mask"] & (
                jnp.abs(g) > alpha * pf * lam * (1.0 + 1e-4) + 1e-9)
            return dict(beta=beta, mask=ks["mask"] | viol, g=g,
                        sweeps=ks["sweeps"] + sweeps, delta=delta,
                        go=jnp.any(viol), rounds=ks["rounds"] + 1)

        def kkt_cond(ks):
            return ks["go"] & (ks["rounds"] < kkt_rounds)

        ks = jax.lax.while_loop(kkt_cond, kkt_body, dict(
            beta=carry["beta"], mask=mask0, g=jnp.zeros((p,), acc),
            sweeps=jnp.zeros((), jnp.int32),
            delta=jnp.asarray(jnp.inf, acc), go=jnp.asarray(True),
            rounds=jnp.zeros((), jnp.int32)))
        beta = ks["beta"]
        beta_orig = beta * isd
        # RSS on the averaged weights, rescaled to the raw-weight deviance
        rss = jnp.maximum(
            yty - 2.0 * (beta_orig @ b) + beta_orig @ (A @ beta_orig), 0.0)
        dev = (wsum * rss).astype(acc)
        nz = pen & (jnp.abs(beta) > 0.0)
        df = jnp.sum(nz).astype(jnp.int32)
        if trace:
            jax.debug.callback(_emit_path_point, k, lam, df, dev,
                               jnp.ones((), jnp.int32), ks["sweeps"],
                               ordered=True)
        new_carry = dict(beta=beta, ever=carry["ever"] | nz, g=ks["g"],
                         lam_prev=lam)
        ys = dict(beta=beta_orig, df=df, dev=dev,
                  iters=jnp.ones((), jnp.int32), sweeps=ks["sweeps"],
                  conv=(ks["delta"] <= cd_tol), kkt_ok=~ks["go"])
        return new_carry, ys

    carry0 = dict(beta=beta_init, ever=jnp.zeros((p,), bool), g=g0,
                  lam_prev=lam_max)
    _, ys = jax.lax.scan(step, carry0,
                         (lams, jnp.arange(lams.shape[0], dtype=jnp.int32)))
    return dict(lambdas=lams, null_dev=(wsum * null_rss).astype(acc),
                b0=b0, sd=sd, **ys)


@functools.partial(jax.jit, static_argnames=_GRAM_STATICS)
def _gram_path_kernel(A, b, s1, yty, wsum, lambdas, lmr, alpha, pf, cd_tol,
                      *, auto_grid, n_lambda, standardize, icol,
                      cd_max_sweeps, kkt_rounds, trace):
    """Jitted solo entry over :func:`_gram_path_core` (docstring there)."""
    return _gram_path_core(
        A, b, s1, yty, wsum, lambdas, lmr, alpha, pf, cd_tol,
        auto_grid=auto_grid, n_lambda=n_lambda, standardize=standardize,
        icol=icol, cd_max_sweeps=cd_max_sweeps, kkt_rounds=kkt_rounds,
        trace=trace)


def _quad_stats_core(X, y, wt, off, *, precision):
    """Single data pass feeding :func:`_gram_path_kernel` for resident
    gaussian/identity fits: the averaged Gramian, column means, response
    quadratic and raw weight sum.  Undecorated for the fleet path kernel;
    :func:`_quad_stats_kernel` is the jitted solo entry."""
    dt = X.dtype
    acc = jnp.float64 if dt == jnp.float64 else jnp.float32
    wsum = jnp.sum(wt.astype(acc))
    wp = (wt / wsum.astype(wt.dtype)).astype(dt)
    z = (y - off).astype(dt)
    A, b = design_gramian(X, z, wp, accum_dtype=acc, precision=precision)
    s1 = design_colsum(X, wp, accum_dtype=acc, precision=precision)
    za = z.astype(acc)
    yty = jnp.sum(wp.astype(acc) * za * za)
    return dict(A=A.astype(acc), b=b.astype(acc), s1=s1.astype(acc),
                yty=yty, wsum=wsum)


@functools.partial(jax.jit, static_argnames=("precision",))
def _quad_stats_kernel(X, y, wt, off, *, precision):
    """Jitted solo entry over :func:`_quad_stats_core`."""
    return _quad_stats_core(X, y, wt, off, precision=precision)


@functools.partial(jax.jit, static_argnames=("cd_max_sweeps",))
def _cd_solve_kernel(As, bs, beta0, lam, alpha, pf, mask, cd_tol, *,
                     cd_max_sweeps):
    """One warm-started elastic-net solve on a standardized Gramian with
    lambda TRACED — the streaming GLM driver calls this once per IRLS
    iteration per lambda and never recompiles across the grid."""
    beta, sweeps, delta = _cd_solve(As, bs, beta0, lam, alpha, pf, mask,
                                    cd_tol, cd_max_sweeps)
    g = bs - As @ beta
    crit = jnp.max(jnp.diag(As) * (beta - beta0) ** 2)
    return dict(beta=beta, g=g, sweeps=sweeps, delta=delta, crit=crit)


# ---------------------------------------------------------------------------
# host driver


def resolve_penalty_vector(penalty, xnames, has_intercept, icol):
    """Expand ``penalty.penalty_factor`` to the full xnames-aligned vector,
    glmnet-rescaled to sum to the number of penalized variables.  The
    intercept entry is forced to 0 (never penalized)."""
    p = len(xnames)
    nvars = p - (1 if icol is not None else 0)
    if nvars == 0:
        raise ValueError("the design has no penalizable columns")
    pf = penalty.penalty_factor
    if pf is None:
        pfv = np.ones(p, np.float64)
    else:
        pfv = np.asarray(pf, np.float64).ravel()
        if icol is not None and pfv.shape[0] == p - 1:
            pfv = np.insert(pfv, icol, 0.0)  # user gave non-intercept factors
        if pfv.shape[0] != p:
            raise ValueError(
                f"penalty_factor must have {p - 1 if icol is not None else p}"
                f" (non-intercept) or {p} entries aligned to xnames, got "
                f"{pfv.shape[0]}")
    if icol is not None:
        pfv[icol] = 0.0
    s = pfv.sum()
    if s <= 0.0:
        raise ValueError(
            "penalty_factor zeroes every column — that is an unpenalized "
            "fit; drop penalty= instead")
    # glmnet internally rescales penalty.factor to sum to nvars
    pfv = pfv * (nvars / s)
    return pfv


def intercept_col(xnames, has_intercept):
    """Index of the intercept column, or None."""
    if not has_intercept:
        return None
    from ..data.model_matrix import INTERCEPT_NAME
    try:
        return xnames.index(INTERCEPT_NAME)
    except ValueError:
        return 0


def fit_path(X, y, *, family="gaussian", link=None, weights=None,
             offset=None, m=None, penalty, xnames=None, yname="y",
             has_intercept=None, kind="glm", verbose=False, trace=None,
             metrics=None, config=None):
    """Fit an elastic-net lambda path; returns a
    :class:`~sparkglm_tpu.penalized.model.PathModel`.

    The resident entry point behind ``penalty=`` on :func:`sparkglm_tpu.lm`
    / :func:`sparkglm_tpu.glm`.  Dispatch: gaussian/identity goes through
    the accumulated-Gramian pair (stats kernel + path kernel, two
    executables, one data pass); every other family runs the fused
    one-executable GLM path kernel."""
    import dataclasses as _dc

    from ..config import DEFAULT, resolve_matmul_precision, x64_enabled
    from ..families.families import resolve as _resolve
    from ..models.validate import (check_finite_vector,
                                   check_response_domain)
    from .penalty import ElasticNet

    if not isinstance(penalty, ElasticNet):
        raise TypeError(
            f"penalty must be an ElasticNet instance, got {type(penalty)!r}")
    if config is None:
        config = DEFAULT
    fam, lnk = _resolve(family, link)
    if not hasattr(X, "shape") or len(X.shape) != 2:
        raise ValueError("X must be a 2-D design")
    n, p = X.shape
    if xnames is None:
        xnames = tuple(f"x{i}" for i in range(p))
    xnames = tuple(xnames)
    if has_intercept is None:
        has_intercept = xnames and "intercept" in xnames
    icol = intercept_col(list(xnames), has_intercept)

    use_f64 = X.dtype == np.float64 and x64_enabled()
    dtype = np.float64 if use_f64 else np.float32

    def _check_len(v, what):
        v = np.asarray(v, np.float64)
        if v.shape != (n,):
            raise ValueError(f"{what} must have shape ({n},), got {v.shape}")
        return v

    y64 = np.asarray(y, np.float64).reshape(-1)
    if y64.shape != (n,):
        raise ValueError(f"y must have shape ({n},), got {y64.shape}")
    wt64 = (np.ones((n,), np.float64) if weights is None
            else _check_len(weights, "weights"))
    check_finite_vector("y", y64)
    check_finite_vector("weights", wt64)
    if m is not None:
        m64 = _check_len(m, "m")
        check_finite_vector("m", m64)
        if fam.name not in ("binomial", "quasibinomial"):
            raise ValueError(
                "group sizes m only apply to the (quasi)binomial family")
        y64 = y64 / np.maximum(m64, 1e-30)  # counts -> proportions
        wt64 = wt64 * m64
    off64 = (np.zeros((n,), np.float64) if offset is None
             else _check_len(offset, "offset"))
    check_finite_vector("offset", off64)
    check_response_domain(fam.name, y64)
    if wt64.sum() <= 0.0:
        raise ValueError("weights sum to zero; nothing to fit")

    pfv = resolve_penalty_vector(penalty, list(xnames), has_intercept, icol)
    explicit = penalty.resolved_lambdas()
    auto_grid = explicit is None
    n_lambda = penalty.grid_size()
    lmr = penalty.min_ratio(n, p - (1 if icol is not None else 0))

    tracer = _obs_trace.as_tracer(trace, verbose=verbose, metrics=metrics)
    on_tpu = jax.default_backend() == "tpu"
    mmp = resolve_matmul_precision(config, n, p, on_tpu)

    Xd = X.astype(dtype)
    yd = y64.astype(dtype)
    wtd = wt64.astype(dtype)
    offd = off64.astype(dtype)
    alpha = np.asarray(penalty.alpha, dtype)
    pf_in = pfv.astype(dtype)
    lam_in = (np.zeros((n_lambda,), dtype) if auto_grid
              else explicit.astype(dtype))
    lmr_in = np.asarray(lmr, dtype)
    gaussian_identity = fam.name == "gaussian" and lnk.name == "identity"

    from ..obs import timing as _obs_timing

    def _run():
        if gaussian_identity:
            before_s = _quad_stats_kernel._cache_size()
            st = _quad_stats_kernel(Xd, yd, wtd, offd, precision=mmp)
            before_p = _gram_path_kernel._cache_size()
            out = _gram_path_kernel(
                st["A"], st["b"], st["s1"], st["yty"], st["wsum"],
                lam_in, lmr_in, alpha, pf_in,
                np.asarray(penalty.cd_tol, dtype),
                auto_grid=auto_grid, n_lambda=n_lambda,
                standardize=penalty.standardize, icol=icol,
                cd_max_sweeps=penalty.cd_max_sweeps,
                kkt_rounds=_KKT_ROUNDS, trace=tracer is not None)
            compiles = ((_quad_stats_kernel._cache_size() - before_s)
                        + (_gram_path_kernel._cache_size() - before_p))
            return out, compiles, "gram_path"
        before = _glm_path_kernel._cache_size()
        out = _glm_path_kernel(
            Xd, yd, wtd, offd, lam_in, lmr_in, alpha, pf_in,
            np.asarray(penalty.tol, dtype),
            np.asarray(penalty.cd_tol, dtype), fam.param_operand(dtype),
            family=fam, link=lnk, auto_grid=auto_grid, n_lambda=n_lambda,
            standardize=penalty.standardize, icol=icol,
            max_iter=penalty.max_iter, cd_max_sweeps=penalty.cd_max_sweeps,
            kkt_rounds=_KKT_ROUNDS, precision=mmp,
            trace=tracer is not None)
        return out, _glm_path_kernel._cache_size() - before, "glm_path"

    from ..data.structured import StructuredDesign
    engine = ("structured" if isinstance(X, StructuredDesign) else "einsum")
    with _obs_trace.ambient(tracer):
        if tracer is not None:
            tracer.emit("fit_start", model="penalized_path",
                        family=fam.name, link=lnk.name,
                        alpha=float(penalty.alpha), n_lambda=n_lambda,
                        n=int(n), p=int(p))
        with _obs_timing.span("path_fit", tracer, device=True) as sp:
            out, compiles, target = _run()
            sp.watch(out)
        if tracer is not None:
            if compiles:
                tracer.emit("compile", target=target, seconds=sp.seconds,
                            executables=int(compiles),
                            gramian_engine=engine)
            jax.effects_barrier()  # drain path_point callbacks before fit_end

    n_ok = int((wt64 > 0).sum())
    return assemble_path_model(
        out, penalty=penalty, fam=fam, lnk=lnk, xnames=xnames, yname=yname,
        n_obs=int(n), n_ok=n_ok, has_intercept=bool(has_intercept),
        kind=kind, engine=engine, tracer=tracer, compiles=int(compiles),
        has_offset=offset is not None)


def assemble_path_model(out, *, penalty, fam, lnk, xnames, yname, n_obs,
                        n_ok, has_intercept, kind, engine, tracer, compiles,
                        has_offset):
    """Shared tail of every path fit (resident and streaming): host-side
    unpacking, the non-convergence warning, path trace aggregates, and the
    :class:`PathModel` record."""
    from .model import PathModel

    lambdas = np.asarray(out["lambdas"], np.float64)
    betas = np.asarray(out["beta"], np.float64)
    dev = np.asarray(out["dev"], np.float64)
    null_dev = float(out["null_dev"])
    df = np.asarray(out["df"], np.int64)
    conv = np.asarray(out["conv"], bool)
    kkt_ok = np.asarray(out["kkt_ok"], bool)
    iters = np.asarray(out["iters"], np.int64)
    sweeps = np.asarray(out["sweeps"], np.int64)
    dev_ratio = 1.0 - dev / null_dev if null_dev > 0 else np.zeros_like(dev)

    if not conv.all():
        import warnings
        bad = int((~conv).sum())
        warnings.warn(
            f"penalized path: {bad}/{len(conv)} lambda points hit the "
            f"iteration cap (max_iter={penalty.max_iter}, "
            f"cd_max_sweeps={penalty.cd_max_sweeps}) before reaching "
            f"tol={penalty.tol:g}; estimates there may be loose",
            stacklevel=3)

    fit_info = None
    if tracer is not None:
        tracer.emit("fit_end", model="penalized_path",
                    n_lambda=int(len(lambdas)),
                    df_max=int(df.max(initial=0)),
                    dev_ratio_max=float(np.max(dev_ratio, initial=0.0)),
                    converged=bool(conv.all()))
        fit_info = tracer.report()
        fit_info["path"] = {
            "n_lambda": int(len(lambdas)),
            "lambda_max": float(lambdas[0]) if len(lambdas) else None,
            "lambda_min": float(lambdas[-1]) if len(lambdas) else None,
            "alpha": float(penalty.alpha),
            "irls_iters_total": int(iters.sum()),
            "cd_sweeps_total": int(sweeps.sum()),
            "kkt_clean": bool(kkt_ok.all()),
            "executables": int(compiles),
        }

    return PathModel(
        lambdas=lambdas, alpha=float(penalty.alpha), coefficients=betas,
        df=df, deviance=dev, dev_ratio=np.asarray(dev_ratio, np.float64),
        null_deviance=null_dev, family=fam.name, link=lnk.name,
        xnames=tuple(xnames), yname=yname, n_obs=int(n_obs), n_ok=int(n_ok),
        n_params=int(len(xnames)), has_intercept=bool(has_intercept),
        standardize=bool(penalty.standardize),
        penalty=penalty, converged=bool(conv.all()),
        kkt_clean=bool(kkt_ok.all()), iterations=int(iters.sum()),
        dispersion_fixed=bool(fam.dispersion_fixed), kind=kind,
        has_offset=bool(has_offset),
        gramian_engine=engine, fit_info=fit_info)
