"""sparkglm-tpu: TPU-native linear & generalized linear models.

A from-scratch JAX/XLA/pjit framework with the capability surface of
cafreeman/sparkGLM (reference at /root/reference): formula-driven OLS and
IRLS-fitted GLMs on row-sharded data over a device mesh, with R-style
summaries, prediction with training-time column matching, and model
persistence.

Quick start::

    import sparkglm_tpu as sg
    model = sg.glm("y ~ x1 + x2 + group", data, family="binomial")
    print(model.summary())
    mu = sg.predict(model, new_data)
"""

from .api import (TermsPrediction, confint_profile, glm, glm_fleet,
                  glm_from_csv, glm_from_json, glm_from_parquet, glm_nb, lm,
                  lm_from_csv, lm_from_json, lm_from_parquet, online_fleet,
                  predict, quantreg, update)
from .capabilities import CapabilityError, capability_lattice, capability_refusal
from .fleet import (FleetModel, FleetPathModel, fit_many, glm_fit_fleet,
                    glm_fit_fleet_path)
from .data.json import read_json, scan_json_levels, scan_json_schema
from .data.parquet import (read_parquet, scan_parquet_levels,
                           scan_parquet_schema)
from .config import DEFAULT, NumericConfig
from .data.formula import Formula, parse_formula
from .data.frame import as_columns, omit_na
from .data.io import (native_available, read_csv, scan_csv_levels,
                      scan_csv_schema)
from .data.model_matrix import Terms, build_terms, model_matrix, transform
from .data.sparse import SparseDesign, SparseLayout
from .data.sparse import from_coo as sparse_from_coo
from .data.sparse import from_csr as sparse_from_csr
from .families.families import (FAMILIES, Family, get_family,
                                negative_binomial, quasi)
from .families.links import LINKS, Link, get_link
from .models.anova import AnovaTable, add1, anova, drop1, step
from .models.diagnostics import (cooks_distance, covratio, dfbeta, dfbetas,
                                 dffits, hatvalues, influence,
                                 influence_measures, rstandard, rstudent)
from .models.glm import GLMModel
from .models.glm import fit as glm_fit
from .models.negbin import fit_nb as glm_fit_nb
from .models.negbin import theta_of
from .models.lm import LMModel
from .models.lm import fit as lm_fit
from .models.serialize import load_model, save_model
from .models.simulate import simulate
from .models.streaming import (glm_fit_streaming, lm_fit_streaming,
                               lm_merge_checkpoints)
from .elastic import glm_fit_elastic, lm_fit_elastic
from .parallel import distributed
from .parallel.mesh import make_mesh, shard_rows, single_device_mesh
from .penalized import ElasticNet, PathModel
from .obs import (FitTracer, FlightRecorder, JsonlSink, MetricsRegistry,
                  RingBufferSink, SLOMonitor, SLOSpec, Telemetry,
                  prometheus_text)
from .online import DriftGate, OnlineLoop, OnlineSuffStats
from .robustreg import (DPSpec, Smoothing, TauPath, ZCDPAccountant,
                        quantile_tau_path)
from .serve import (AsyncEngine, BatchPolicy, EnginePolicy, FamilyScorer,
                    MicroBatcher, ModelFamily, ModelRegistry,
                    ReplicatedScorer, Scorer)
from .utils import profiling
from . import elastic, fleet, obs, online, robust, robustreg, serve

__version__ = "0.1.0"

__all__ = [
    "lm", "glm", "predict", "update", "lm_fit", "glm_fit",
    "lm_from_csv", "glm_from_csv",
    "lm_from_parquet", "glm_from_parquet",
    "lm_from_json", "glm_from_json",
    "read_parquet", "scan_parquet_schema", "scan_parquet_levels",
    "read_json", "scan_json_schema", "scan_json_levels",
    "lm_fit_streaming", "glm_fit_streaming",
    "elastic", "lm_fit_elastic", "glm_fit_elastic", "lm_merge_checkpoints",
    "LMModel", "GLMModel", "load_model", "save_model", "simulate",
    "ElasticNet", "PathModel",
    "anova", "add1", "drop1", "step", "AnovaTable", "confint_profile",
    "TermsPrediction",
    "hatvalues", "rstandard", "rstudent", "cooks_distance",
    "dfbeta", "dfbetas", "dffits", "covratio", "influence",
    "influence_measures",
    "Family", "Link", "FAMILIES", "LINKS", "get_family", "get_link",
    "quasi", "negative_binomial", "glm_nb", "glm_fit_nb", "theta_of",
    "SparseDesign", "SparseLayout", "sparse_from_csr", "sparse_from_coo",
    "Formula", "parse_formula", "Terms", "build_terms", "model_matrix",
    "transform", "as_columns", "omit_na", "read_csv", "scan_csv_schema",
    "scan_csv_levels",
    "native_available",
    "make_mesh", "shard_rows", "single_device_mesh", "distributed",
    "profiling",
    "NumericConfig", "DEFAULT",
    "robust",
    "obs", "FitTracer", "MetricsRegistry", "JsonlSink", "RingBufferSink",
    "Telemetry", "SLOSpec", "SLOMonitor", "FlightRecorder",
    "prometheus_text",
    "serve", "ModelRegistry", "Scorer", "MicroBatcher", "BatchPolicy",
    "AsyncEngine", "EnginePolicy", "ReplicatedScorer",
    "fleet", "fit_many", "glm_fit_fleet", "glm_fleet", "FleetModel",
    "FleetPathModel", "glm_fit_fleet_path",
    "CapabilityError", "capability_lattice", "capability_refusal",
    "ModelFamily", "FamilyScorer",
    "online", "online_fleet", "OnlineLoop", "OnlineSuffStats", "DriftGate",
    "robustreg", "quantreg", "quantile_tau_path", "TauPath",
    "Smoothing", "DPSpec", "ZCDPAccountant",
]
