"""Versioned in-process model registry with deploy/rollback.

The reference's "model persistence" story is keeping the JVM object alive
(SURVEY.md §5); its serving story is nonexistent.  Here serving is explicit:
a :class:`ModelRegistry` holds every registered VERSION of each named model
(versions are immutable once registered — auto-numbered 1, 2, 3, ...), one
of which is *deployed* at a time.  ``deploy``/``rollback`` move the pointer;
``scorer()`` hands out a compiled-cache :class:`~.engine.Scorer` for the
deployed version.

Deployment history is a stack: ``rollback()`` restores the previously
deployed version (and can be repeated).  Registering a new version does NOT
auto-deploy it unless asked (``deploy=True``) or it is the first version of
the name — staging-by-default, so a bad artifact cannot take traffic by
merely being loaded.

Because the scoring kernel takes coefficients as runtime arguments (one
executable per (signature, bucket), NOT per model — models/scoring.py),
deploying a new version with the same design signature reuses the already-
warm executables: deploy/rollback is recompile-free hot-swapping.

Models loaded from disk come through ``models/serialize.py``, which
verifies ``schema_version`` and fails legibly (naming the unknown keys) on
artifacts written by a newer trainer — the registry never scores an
artifact whose fields it might silently drop.
"""

from __future__ import annotations

import threading

from .engine import Scorer

__all__ = ["ModelRegistry"]


class _Entry:
    __slots__ = ("versions", "deployed", "history")

    def __init__(self):
        self.versions: dict[int, object] = {}
        self.deployed: int | None = None
        self.history: list[int] = []  # deploy stack; [-1] == deployed


class ModelRegistry:
    """Thread-safe named/versioned model store; see module docstring."""

    def __init__(self, *, metrics=None):
        self._lock = threading.RLock()
        self._entries: dict[str, _Entry] = {}
        self._scorers: dict[tuple, Scorer] = {}
        self.metrics = metrics

    # -- registration --------------------------------------------------------

    def register(self, name: str, model, *, deploy: bool | None = None) -> int:
        """Add ``model`` as the next version of ``name``; returns the
        version number.  The model carries its own training ``Terms`` (and
        by-name offset), so raw column data scores through the exact
        training transform.  First version of a name auto-deploys;
        later ones stage unless ``deploy=True``.
        """
        with self._lock:
            e = self._entries.setdefault(name, _Entry())
            version = max(e.versions, default=0) + 1
            e.versions[version] = model
            if deploy or (deploy is None and e.deployed is None):
                self._deploy_locked(name, e, version)
            if self.metrics is not None:
                self.metrics.counter(f"registry.{name}.registered").inc()
            return version

    def load(self, name: str, path: str, *, deploy: bool | None = None) -> int:
        """Register a model artifact from disk (``models/serialize.py``
        format; schema_version-checked)."""
        from ..models.serialize import load_model
        return self.register(name, load_model(path), deploy=deploy)

    # -- deployment ----------------------------------------------------------

    def _deploy_locked(self, name: str, e: _Entry, version: int) -> None:
        e.deployed = version
        e.history.append(version)
        # a scorer is version-pinned; drop cached ones for this name so the
        # next scorer() resolves the new deployment (executables persist in
        # the jit cache — same signature means no recompile)
        for k in [k for k in self._scorers if k[0] == name]:
            del self._scorers[k]
        if self.metrics is not None:
            self.metrics.gauge(f"registry.{name}.deployed").set(version)

    def deploy(self, name: str, version: int) -> None:
        """Point ``name`` at ``version`` (must be registered)."""
        with self._lock:
            e = self._require(name)
            if version not in e.versions:
                raise KeyError(
                    f"model {name!r} has no version {version}; registered: "
                    f"{sorted(e.versions)}")
            self._deploy_locked(name, e, version)

    def rollback(self, name: str) -> int:
        """Re-deploy the previously deployed version; returns it.  Raises
        if there is no earlier deployment to roll back to."""
        with self._lock:
            e = self._require(name)
            if len(e.history) < 2:
                raise RuntimeError(
                    f"model {name!r} has no prior deployment to roll back "
                    f"to (history: {e.history})")
            e.history.pop()            # discard the current deployment
            version = e.history.pop()  # _deploy_locked re-appends it
            self._deploy_locked(name, e, version)
            return version

    # -- lookup --------------------------------------------------------------

    def _require(self, name: str) -> _Entry:
        e = self._entries.get(name)
        if e is None:
            raise KeyError(
                f"no model registered under {name!r}; have "
                f"{sorted(self._entries)}")
        return e

    def names(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._entries))

    def versions(self, name: str) -> tuple[int, ...]:
        with self._lock:
            return tuple(sorted(self._require(name).versions))

    def deployed_version(self, name: str) -> int | None:
        with self._lock:
            return self._require(name).deployed

    def model(self, name: str, version: int | None = None):
        """The deployed model (or a specific registered version)."""
        with self._lock:
            e = self._require(name)
            v = e.deployed if version is None else version
            if v is None:
                raise RuntimeError(f"model {name!r} has no deployed version")
            if v not in e.versions:
                raise KeyError(
                    f"model {name!r} has no version {v}; registered: "
                    f"{sorted(e.versions)}")
            return e.versions[v]

    def scorer(self, name: str, **kwargs) -> Scorer:
        """A :class:`Scorer` for the deployed version of ``name``, cached
        per (name, version, scoring options) so repeated calls share
        compile/bucket state.  ``kwargs`` go to :class:`Scorer` (``type=``,
        ``se_fit=``, ``min_bucket=``, ...)."""
        with self._lock:
            e = self._require(name)
            if e.deployed is None:
                raise RuntimeError(f"model {name!r} has no deployed version")
            metrics = kwargs.pop("metrics", self.metrics)
            key = (name, e.deployed, tuple(sorted(kwargs.items())))
            sc = self._scorers.get(key)
            if sc is None:
                sc = Scorer(e.versions[e.deployed], name=name,
                            metrics=metrics, **kwargs)
                self._scorers[key] = sc
            return sc
