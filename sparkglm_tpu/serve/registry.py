"""Versioned in-process model registry with deploy/rollback.

The reference's "model persistence" story is keeping the JVM object alive
(SURVEY.md §5); its serving story is nonexistent.  Here serving is explicit:
a :class:`ModelRegistry` holds every registered VERSION of each named model
(versions are immutable once registered — auto-numbered 1, 2, 3, ...), one
of which is *deployed* at a time.  ``deploy``/``rollback`` move the pointer;
``scorer()`` hands out a compiled-cache :class:`~.engine.Scorer` for the
deployed version.

Deployment history is a stack: ``rollback()`` restores the previously
deployed version (and can be repeated).  Registering a new version does NOT
auto-deploy it unless asked (``deploy=True``) or it is the first version of
the name — staging-by-default, so a bad artifact cannot take traffic by
merely being loaded.

Because the scoring kernel takes coefficients as runtime arguments (one
executable per (signature, bucket), NOT per model — models/scoring.py),
deploying a new version with the same design signature reuses the already-
warm executables: deploy/rollback is recompile-free hot-swapping.

Models loaded from disk come through ``models/serialize.py``, which
verifies ``schema_version`` and fails legibly (naming the unknown keys) on
artifacts written by a newer trainer — the registry never scores an
artifact whose fields it might silently drop.
"""

from __future__ import annotations

import threading

import numpy as np

from .engine import FamilyScorer, Scorer

__all__ = ["ModelFamily", "ModelRegistry"]


class _Entry:
    __slots__ = ("versions", "deployed", "history")

    def __init__(self):
        self.versions: dict[int, object] = {}
        self.deployed: int | None = None
        self.history: list[int] = []  # deploy stack; [-1] == deployed


class ModelRegistry:
    """Thread-safe named/versioned model store; see module docstring."""

    def __init__(self, *, metrics=None):
        self._lock = threading.RLock()
        self._entries: dict[str, _Entry] = {}
        self._scorers: dict[tuple, Scorer] = {}
        self.metrics = metrics

    # -- registration --------------------------------------------------------

    def register(self, name: str, model, *, deploy: bool | None = None) -> int:
        """Add ``model`` as the next version of ``name``; returns the
        version number.  The model carries its own training ``Terms`` (and
        by-name offset), so raw column data scores through the exact
        training transform.  First version of a name auto-deploys;
        later ones stage unless ``deploy=True``.
        """
        with self._lock:
            e = self._entries.setdefault(name, _Entry())
            version = max(e.versions, default=0) + 1
            e.versions[version] = model
            if deploy or (deploy is None and e.deployed is None):
                self._deploy_locked(name, e, version)
            if self.metrics is not None:
                self.metrics.counter(f"registry.{name}.registered").inc()
            return version

    def load(self, name: str, path: str, *, deploy: bool | None = None) -> int:
        """Register a model artifact from disk (``models/serialize.py``
        format; schema_version-checked)."""
        from ..models.serialize import load_model
        return self.register(name, load_model(path), deploy=deploy)

    # -- deployment ----------------------------------------------------------

    def _deploy_locked(self, name: str, e: _Entry, version: int) -> None:
        e.deployed = version
        e.history.append(version)
        # a scorer is version-pinned; drop cached ones for this name so the
        # next scorer() resolves the new deployment (executables persist in
        # the jit cache — same signature means no recompile)
        for k in [k for k in self._scorers if k[0] == name]:
            del self._scorers[k]
        if self.metrics is not None:
            self.metrics.gauge(f"registry.{name}.deployed").set(version)

    def deploy(self, name: str, version: int) -> None:
        """Point ``name`` at ``version`` (must be registered)."""
        with self._lock:
            e = self._require(name)
            if version not in e.versions:
                raise KeyError(
                    f"model {name!r} has no version {version}; registered: "
                    f"{sorted(e.versions)}")
            self._deploy_locked(name, e, version)

    def rollback(self, name: str) -> int:
        """Re-deploy the previously deployed version; returns it.  Raises
        if there is no earlier deployment to roll back to."""
        with self._lock:
            e = self._require(name)
            if len(e.history) < 2:
                raise RuntimeError(
                    f"model {name!r} has no prior deployment to roll back "
                    f"to (history: {e.history})")
            e.history.pop()            # discard the current deployment
            version = e.history.pop()  # _deploy_locked re-appends it
            self._deploy_locked(name, e, version)
            return version

    # -- lookup --------------------------------------------------------------

    def _require(self, name: str) -> _Entry:
        e = self._entries.get(name)
        if e is None:
            raise KeyError(
                f"no model registered under {name!r}; have "
                f"{sorted(self._entries)}")
        return e

    def names(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._entries))

    def versions(self, name: str) -> tuple[int, ...]:
        with self._lock:
            return tuple(sorted(self._require(name).versions))

    def deployed_version(self, name: str) -> int | None:
        with self._lock:
            return self._require(name).deployed

    def model(self, name: str, version: int | None = None):
        """The deployed model (or a specific registered version)."""
        with self._lock:
            e = self._require(name)
            v = e.deployed if version is None else version
            if v is None:
                raise RuntimeError(f"model {name!r} has no deployed version")
            if v not in e.versions:
                raise KeyError(
                    f"model {name!r} has no version {v}; registered: "
                    f"{sorted(e.versions)}")
            return e.versions[v]

    def scorer(self, name: str, **kwargs) -> Scorer:
        """A :class:`Scorer` for the deployed version of ``name``, cached
        per (name, version, scoring options) so repeated calls share
        compile/bucket state.  ``kwargs`` go to :class:`Scorer` (``type=``,
        ``se_fit=``, ``min_bucket=``, ...)."""
        with self._lock:
            e = self._require(name)
            if e.deployed is None:
                raise RuntimeError(f"model {name!r} has no deployed version")
            metrics = kwargs.pop("metrics", self.metrics)
            key = (name, e.deployed, tuple(sorted(kwargs.items())))
            sc = self._scorers.get(key)
            if sc is None:
                sc = Scorer(e.versions[e.deployed], name=name,
                            metrics=metrics, **kwargs)
                self._scorers[key] = sc
            return sc


class ModelFamily:
    """Per-tenant versioned registry over ONE shared design signature.

    A fleet fit (``fleet/``) produces thousands of per-segment models that
    share columns, family and link.  :class:`ModelRegistry` treats each as
    an unrelated name; a ``ModelFamily`` instead keys on *tenant* and
    enforces the shared signature — which is what lets serving stack every
    tenant's deployed coefficients into one (T, p) matrix and score a mixed
    batch of ``(tenant, x)`` requests in ONE dispatch
    (:class:`~.engine.FamilyScorer`).

    Per tenant, the deployment semantics are exactly ModelRegistry's:
    versions are immutable and auto-numbered, the first registered version
    auto-deploys, later ones stage unless ``deploy=True``, and
    ``rollback`` pops the per-tenant deploy stack.  Any deploy change bumps
    the family *generation*; scorers are pinned to the generation they were
    built from, so a stale scorer is never silently served — ``scorer()``
    hands out a fresh (cached per generation+options) one.

    Persistence: ``family.save(path)`` / ``models/serialize.py`` round-trip
    the whole family — every registered version plus the deploy history —
    through the ``_export()``/``_restore()`` hooks.

    ``history_cap`` bounds each tenant's deploy STACK (a continuously
    redeploying online loop would otherwise grow it without limit —
    sparkglm_tpu/online redeploys on every accepted refresh).  The default
    keeps the most recent :data:`HISTORY_CAP` deployments per tenant —
    more than any sane rollback chain — and ``history_cap=None`` opts back
    in to the full unbounded history.  Registered versions themselves are
    never dropped; only the rollback stack is trimmed.
    """

    #: default per-tenant deploy-stack bound (``history_cap=None`` unbounds)
    HISTORY_CAP = 64

    def __init__(self, name: str, *, metrics=None,
                 history_cap: int | None = HISTORY_CAP):
        if history_cap is not None and int(history_cap) < 2:
            raise ValueError(
                f"history_cap must be >= 2 (rollback needs the prior "
                f"deployment) or None for unbounded, got {history_cap!r}")
        self.history_cap = None if history_cap is None else int(history_cap)
        self._lock = threading.RLock()
        self._entries: dict[str, _Entry] = {}
        self._scorers: dict[tuple, FamilyScorer] = {}
        # replicated scorers are generation-FOLLOWING (refresh() re-snapshots
        # recompile-free), so unlike _scorers they survive deploys
        self._replicated: dict[tuple, object] = {}
        self._generation = 0
        self.name = str(name)
        self.metrics = metrics
        # shared design signature — fixed by the first registered model
        self._xnames: tuple | None = None
        self._family: str | None = None
        self._link: str | None = None

    # -- signature -----------------------------------------------------------

    @property
    def xnames(self) -> tuple | None:
        return self._xnames

    @property
    def family(self) -> str | None:
        return self._family

    @property
    def link(self) -> str | None:
        return self._link

    @property
    def n_params(self) -> int | None:
        return None if self._xnames is None else len(self._xnames)

    def _check_signature(self, tenant: str, model) -> None:
        xn = tuple(getattr(model, "xnames", ()) or ())
        fam = getattr(model, "family", None)
        lnk = getattr(model, "link", None)
        if self._xnames is None:
            self._xnames, self._family, self._link = xn, fam, lnk
            return
        if xn != self._xnames:
            raise ValueError(
                f"tenant {tenant!r}: model columns {list(xn)} do not match "
                f"family {self.name!r} signature {list(self._xnames)} — a "
                "ModelFamily shares ONE design layout so batched scoring "
                "can stack coefficients")
        if (fam, lnk) != (self._family, self._link):
            raise ValueError(
                f"tenant {tenant!r}: model is {fam}({lnk}); family "
                f"{self.name!r} is {self._family}({self._link})")

    # -- registration --------------------------------------------------------

    def register(self, tenant: str, model, *,
                 deploy: bool | None = None) -> int:
        """Add ``model`` as the next version for ``tenant``; returns the
        version number.  First version of a tenant auto-deploys; later
        ones stage unless ``deploy=True``."""
        tenant = str(tenant)
        with self._lock:
            self._check_signature(tenant, model)
            e = self._entries.setdefault(tenant, _Entry())
            version = max(e.versions, default=0) + 1
            e.versions[version] = model
            if deploy or (deploy is None and e.deployed is None):
                self._deploy_locked(tenant, e, version)
            if self.metrics is not None:
                self.metrics.counter(
                    f"family.{self.name}.registered").inc()
            return version

    @classmethod
    def from_fleet(cls, fleet, name: str, *, metrics=None) -> "ModelFamily":
        """Build a family from a :class:`~sparkglm_tpu.fleet.FleetModel`:
        one tenant per fleet group, each group's solo-equivalent
        ``GLMModel`` registered as version 1 and deployed."""
        fam = cls(name, metrics=metrics)
        for label, model in fleet.models():
            fam.register(str(label), model)
        return fam

    # -- deployment ----------------------------------------------------------

    def _deploy_locked(self, tenant: str, e: _Entry, version: int) -> None:
        e.deployed = version
        e.history.append(version)
        if self.history_cap is not None and len(e.history) > self.history_cap:
            del e.history[:len(e.history) - self.history_cap]
        self._generation += 1
        self._scorers.clear()  # scorers pin a coefficient snapshot
        if self.metrics is not None:
            self.metrics.gauge(
                f"family.{self.name}.{tenant}.deployed").set(version)

    def deploy(self, tenant: str, version: int) -> None:
        with self._lock:
            e = self._require(tenant)
            if version not in e.versions:
                raise KeyError(
                    f"tenant {tenant!r} has no version {version}; "
                    f"registered: {sorted(e.versions)}")
            self._deploy_locked(tenant, e, version)

    def rollback(self, tenant: str) -> int:
        """Re-deploy the tenant's previously deployed version."""
        with self._lock:
            e = self._require(tenant)
            if len(e.history) < 2:
                raise RuntimeError(
                    f"tenant {tenant!r} has no prior deployment to roll "
                    f"back to (history: {e.history})")
            e.history.pop()
            version = e.history.pop()  # _deploy_locked re-appends it
            self._deploy_locked(tenant, e, version)
            return version

    # -- lookup --------------------------------------------------------------

    def _require(self, tenant: str) -> _Entry:
        e = self._entries.get(str(tenant))
        if e is None:
            raise KeyError(
                f"no tenant {tenant!r} in family {self.name!r}; have "
                f"{sorted(self._entries)[:8]}"
                f"{'...' if len(self._entries) > 8 else ''}")
        return e

    def tenants(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._entries))

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def versions(self, tenant: str) -> tuple[int, ...]:
        with self._lock:
            return tuple(sorted(self._require(tenant).versions))

    def deployed_version(self, tenant: str) -> int | None:
        with self._lock:
            return self._require(tenant).deployed

    def model(self, tenant: str, version: int | None = None):
        with self._lock:
            e = self._require(tenant)
            v = e.deployed if version is None else version
            if v is None:
                raise RuntimeError(
                    f"tenant {tenant!r} has no deployed version")
            if v not in e.versions:
                raise KeyError(
                    f"tenant {tenant!r} has no version {v}; registered: "
                    f"{sorted(e.versions)}")
            return e.versions[v]

    def generation(self) -> int:
        """Deploy-state counter; bumps on every deploy/rollback.  Scorers
        record the generation they snapshot."""
        with self._lock:
            return self._generation

    def deployed_matrix(self) -> tuple[tuple[str, ...], np.ndarray]:
        """``(tenants, (T, p) float64 coefficients)`` for the deployed
        version of every tenant — the FamilyScorer gather table."""
        with self._lock:
            tenants = tuple(sorted(self._entries))
            if not tenants:
                raise RuntimeError(
                    f"family {self.name!r} has no tenants to serve")
            rows = []
            for t in tenants:
                e = self._entries[t]
                if e.deployed is None:
                    raise RuntimeError(
                        f"tenant {t!r} has no deployed version")
                rows.append(np.asarray(
                    e.versions[e.deployed].coefficients, np.float64))
            return tenants, np.stack(rows)

    # -- scoring -------------------------------------------------------------

    def scorer(self, **kwargs) -> FamilyScorer:
        """A :class:`~.engine.FamilyScorer` over the family's CURRENT
        deploy state, cached per (generation, options) — any
        deploy/rollback invalidates the cache so the next call snapshots
        fresh coefficients.  ``kwargs`` go to :class:`FamilyScorer`
        (``type=``, ``min_bucket=``, ``challenger=``, ``shadow=``, ...)."""
        with self._lock:
            metrics = kwargs.pop("metrics", self.metrics)
            key = (self._generation,
                   tuple(sorted((k, _freeze(v))
                                for k, v in kwargs.items())))
            sc = self._scorers.get(key)
            if sc is None:
                sc = FamilyScorer(self, metrics=metrics, **kwargs)
                self._scorers[key] = sc
            return sc

    def replicated_scorer(self, **kwargs):
        """A :class:`~.async_engine.ReplicatedScorer` over this family,
        cached per options only — NOT per generation: replicated scorers
        follow deploys/rollbacks by ``refresh()`` (a recompile-free table
        re-snapshot), so the same instance (and its warm per-replica
        executables) serves across generations.  ``kwargs`` go to
        :class:`ReplicatedScorer` (``devices=``, ``precision=``, ...)."""
        from .async_engine import ReplicatedScorer
        with self._lock:
            metrics = kwargs.pop("metrics", self.metrics)
            key = tuple(sorted((k, _freeze(v)) for k, v in kwargs.items()))
            sc = self._replicated.get(key)
        if sc is None:
            # construct outside the lock: the first snapshot device_puts
            # tables to every replica
            sc = ReplicatedScorer(self, metrics=metrics, **kwargs)
            with self._lock:
                sc = self._replicated.setdefault(key, sc)
        sc.refresh()
        return sc

    def async_engine(self, policy=None, *, telemetry=None, health=None,
                     fault_plan=None, **kwargs):
        """A fresh :class:`~.async_engine.AsyncEngine` over this family's
        :meth:`replicated_scorer` (``kwargs`` select/configure it).  The
        caller owns the engine's lifecycle — use as a context manager or
        ``close()`` it; the underlying scorer stays cached here.

        ``telemetry=`` (an :class:`~..obs.export.Telemetry`) turns on the
        request-scoped tracing / SLO / export plane; without it the
        engine keeps the family's metrics registry only.  ``health=`` (a
        :class:`~.health.HealthPolicy`) configures the self-healing
        plane — watchdog deadline, hedge budget, breaker thresholds;
        ``fault_plan=`` injects seeded serving faults (chaos testing)."""
        from .async_engine import AsyncEngine
        return AsyncEngine(self.replicated_scorer(**kwargs), policy,
                           metrics=None if telemetry is not None
                           else self.metrics,
                           name=self.name, telemetry=telemetry,
                           health=health, fault_plan=fault_plan)

    # -- persistence ---------------------------------------------------------

    def save(self, path) -> None:
        from ..models.serialize import save_model
        save_model(self, path)

    def _export(self):
        """Serialization hook: ``(members, fam_meta)`` where members is a
        deterministic ``[(tenant, version, model), ...]`` over EVERY
        registered version and fam_meta carries the deploy state."""
        with self._lock:
            members = []
            for tenant in sorted(self._entries):
                e = self._entries[tenant]
                for version in sorted(e.versions):
                    members.append((tenant, version, e.versions[version]))
            fam_meta = dict(
                name=self.name,
                history_cap=self.history_cap,
                generation=self._generation,
                deployed={t: self._entries[t].deployed
                          for t in sorted(self._entries)},
                history={t: list(self._entries[t].history)
                         for t in sorted(self._entries)})
            return members, fam_meta

    @classmethod
    def _restore(cls, members, meta) -> "ModelFamily":
        """Serialization hook: rebuild from ``_export()`` output."""
        fam = cls(meta["name"],
                  history_cap=meta.get("history_cap", cls.HISTORY_CAP))
        for tenant, version, model in members:
            fam._check_signature(tenant, model)
            e = fam._entries.setdefault(tenant, _Entry())
            e.versions[int(version)] = model
        for tenant, dep in (meta.get("deployed") or {}).items():
            e = fam._entries.get(tenant)
            if e is not None:
                e.deployed = None if dep is None else int(dep)
                e.history = [int(v)
                             for v in (meta.get("history") or {})
                             .get(tenant, [] if dep is None else [dep])]
        # the generation counter round-trips (artifacts older than v5's
        # growth support carry none — they restore at 0, a fresh line of
        # generations): serving tiers that poll a serialized family
        # (serve/pool.FamilyStore) compare generations across processes,
        # so a restored family must report the generation it was
        # published at, not restart its own clock
        fam._generation = int(meta.get("generation", 0))
        return fam


def _freeze(v):
    """Hashable view of a scorer kwarg for the per-options cache key."""
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple, set)):
        return tuple(_freeze(x) for x in v)
    return v
