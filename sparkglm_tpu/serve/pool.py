"""Multi-engine serving tier: N :class:`AsyncEngine` instances over one
published :class:`ModelFamily`, engine-level health, zero lost requests.

One engine is one event loop, one scheduler, one process's worth of blast
radius.  :class:`EnginePool` runs several engines side by side — each
over its OWN :class:`ReplicatedScorer` (private device tables, private
executable warm state) — and routes requests across them through the
same circuit-breaker state machine the engines use per replica
(serve/health.py, one level up): a dead engine is ejected after
``eject_after`` consecutive submission failures, its traffic re-routes
to the survivors, and because every engine serves the same
generation-synced family at the same padded tenant bucket, re-routing
never recompiles anything.

Cross-process family sync is a file: :class:`FamilyStore` publishes the
serialized family (models/serialize.py v5 — byte-deterministic) next to
a GENERATION stamp, blob first, stamp second, both atomic renames
(robust/checkpoint.py), so a poller that sees generation g can always
load a blob of at least generation g.  :meth:`EnginePool.sync` polls the
stamp — a cheap stat-and-read — and on movement loads the blob once and
re-registers the changed members into the pool's family; every engine's
scorer then re-snapshots recompile-free on its next batch (the
``refresh()``-per-batch hook growth and deploys already ride).

Loss accounting is the contract the chaos test enforces: ``submit``
either returns a Future that RESOLVES (value or typed error) or raises
:class:`Overloaded` synchronously — a request accepted by the pool is
never dropped when an engine dies mid-queue, because a submission
failure on one engine falls through to the next admissible engine in
the same call, and a future failed by a dying engine's drain is retried
once on a survivor by the pool's resubmit hook.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from ..robust.checkpoint import atomic_write_bytes
from ..robust.retry import Overloaded, ReplicaUnavailable
from .async_engine import AsyncEngine, ReplicatedScorer
from .health import ReplicaHealth

__all__ = ["FamilyStore", "EnginePool"]

_BLOB = "family.npz"
_STAMP = "GENERATION"


class FamilyStore:
    """Single-writer published-family directory (module doc).

    The WRITER (the learning plane / growth coordinator) calls
    :meth:`publish` after deploys; READERS (engine pools, possibly in
    other processes) poll :meth:`generation` and :meth:`load`.  Ordering
    contract: the blob rename lands BEFORE the stamp rename, so the
    stamp never advertises a generation the blob does not carry.
    """

    def __init__(self, directory):
        self.directory = os.fspath(directory)
        os.makedirs(self.directory, exist_ok=True)

    @property
    def blob_path(self) -> str:
        return os.path.join(self.directory, _BLOB)

    def publish(self, family) -> int:
        """Serialize ``family`` and publish it; returns the generation
        stamped.  Byte-deterministic: same family state, same blob."""
        import io
        from ..models.serialize import save_model
        gen = family.generation()
        buf = io.BytesIO()
        save_model(family, buf)
        atomic_write_bytes(self.blob_path, buf.getvalue())
        atomic_write_bytes(os.path.join(self.directory, _STAMP),
                           f"{gen}\n".encode())
        return gen

    def generation(self) -> int | None:
        """The published generation, or None before the first publish —
        a cheap poll (one small read, no deserialization)."""
        try:
            with open(os.path.join(self.directory, _STAMP), "rb") as f:
                return int(f.read().strip())
        except (FileNotFoundError, ValueError):
            return None

    def load(self):
        """Deserialize the published family (generation included — the
        registry persists its counter)."""
        from ..models.serialize import load_model
        return load_model(self.blob_path)


class EnginePool:
    """N async engines over one family, health-routed (module doc).

    Args:
      family: the served :class:`ModelFamily`, or a :class:`FamilyStore`
        to load it from (and poll via :meth:`sync`).
      n_engines: engines to run (>= 1); each gets a private
        :class:`ReplicatedScorer` over ``devices`` (default: all).
      policy: per-engine :class:`EnginePolicy`.
      health: engine-level :class:`HealthPolicy` (breaker thresholds);
        each engine also keeps its own per-replica health plane.
      fault_plan: a :class:`~..robust.faults.FaultPlan` whose
        ``on_engine_submit`` hook fires on every routed submission — the
        chaos test's dead-engine injection.
      engine_fault_plans: optional ``{engine_index: FaultPlan}`` handed
        to the named engines themselves (replica-level faults INSIDE an
        engine — the mid-flight-death chaos scenario: an engine whose
        replicas all die fails its queued futures with
        ``ReplicaUnavailable`` and the pool resubmits them on a
        survivor).
      engine_health: per-replica :class:`HealthPolicy` forwarded to each
        engine (e.g. a small ``max_attempts`` so a fully-dead engine
        fails futures out fast instead of retrying forever).
      telemetry / metrics: obs/ wiring shared by the engines.
      store: optional :class:`FamilyStore` to poll (implied when
        ``family`` IS a store).
    """

    def __init__(self, family, n_engines: int = 2, *, policy=None,
                 devices=None, precision=None, health=None,
                 fault_plan=None, engine_fault_plans=None,
                 engine_health=None, telemetry=None, metrics=None,
                 store=None, name: str | None = None):
        if int(n_engines) < 1:
            raise ValueError(f"n_engines must be >= 1, got {n_engines}")
        if isinstance(family, FamilyStore):
            store = family
            family = store.load()
        self.family = family
        self.store = store
        self.telemetry = telemetry
        self.name = name if name is not None else f"{family.name}-pool"
        self.n_engines = int(n_engines)
        self._fault_plan = fault_plan
        self._lock = threading.Lock()
        self._rr = 0                       # round-robin cursor
        self.resubmits = 0                 # futures retried on a survivor
        self.lost = 0                      # futures no engine could take
        self._synced_generation = family.generation()
        self.scorers = [
            ReplicatedScorer(family, devices=devices, precision=precision,
                             name=f"{self.name}-e{i}")
            for i in range(self.n_engines)]
        plans = engine_fault_plans or {}
        self.engines = [
            AsyncEngine(self.scorers[i], policy,
                        name=f"{self.name}-e{i}", telemetry=telemetry,
                        metrics=metrics, health=engine_health,
                        fault_plan=plans.get(i))
            for i in range(self.n_engines)]
        self.health = ReplicaHealth(
            self.n_engines, health,
            emit=self._health_emit)

    # -- telemetry ------------------------------------------------------------

    def _emit(self, kind: str, **fields) -> None:
        """Pool-level events ride the same tracer the engines use (the
        telemetry's when attached, ambient otherwise)."""
        self.engines[0]._emit(kind, pool=self.name, **fields)

    def _health_emit(self, kind: str, **fields) -> None:
        """Engine-level health transitions keep their replica_* kinds
        (so flight-recorder triggers still fire) but are tagged with the
        pool scope — ``replica`` in these events is an ENGINE index."""
        self._emit(kind, scope="engine", **fields)

    # -- routing --------------------------------------------------------------

    def _order(self) -> list:
        """Round-robin engine order starting at the rotating cursor —
        every candidate appears once, so a submission can fall through
        every admissible engine before giving up."""
        with self._lock:
            start = self._rr
            self._rr = (self._rr + 1) % self.n_engines
        return [(start + i) % self.n_engines
                for i in range(self.n_engines)]

    def submit(self, data, *, tenant: str | None = None, offset=None,
               deadline: float | None = None):
        """Route one request to a healthy engine; returns its Future.

        Falls through engines on submission failure (injected fault,
        closed engine, full queue): the request is only lost if EVERY
        engine refuses, and that surfaces synchronously as the last
        refusal — an accepted Future always resolves.  A future failed
        later by a dying engine's drain is resubmitted once on a
        survivor (``_resubmit``), keeping the zero-lost-requests
        contract under mid-flight engine death.
        """
        last_exc: Exception | None = None
        for i in self._order():
            if not self.health.admit(i):
                continue
            try:
                if self._fault_plan is not None:
                    self._fault_plan.on_engine_submit(i)
                inner = self.engines[i].submit(
                    data, tenant=tenant, offset=offset, deadline=deadline)
            except (ReplicaUnavailable, RuntimeError, Overloaded) as exc:
                self.health.on_failure(i, exc)
                last_exc = exc
                continue
            self.health.on_success(i)
            outer = _RoutedFuture.wrap(
                self, inner, i, data, tenant, offset, deadline)
            return outer
        with self._lock:
            self.lost += 1
        self._emit("pool_lost", tenant=tenant, where="submit")
        raise last_exc if last_exc is not None else Overloaded(
            f"no admissible engine in pool {self.name!r}")

    def _resubmit(self, outer, exc, engine, data, tenant, offset,
                  deadline) -> bool:
        """One survivor retry for a future failed by engine death
        (RuntimeError from a closing engine / ReplicaUnavailable).
        Returns whether the request was re-routed."""
        self.health.on_failure(engine, exc)
        for i in self._order():
            if i == engine or not self.health.admit(i):
                continue
            try:
                if self._fault_plan is not None:
                    self._fault_plan.on_engine_submit(i)
                inner = self.engines[i].submit(
                    data, tenant=tenant, offset=offset, deadline=deadline)
            except (ReplicaUnavailable, RuntimeError, Overloaded) as e2:
                self.health.on_failure(i, e2)
                continue
            self.health.on_success(i)
            with self._lock:
                self.resubmits += 1
            self._emit("pool_resubmit", from_engine=int(engine),
                       to_engine=int(i), tenant=tenant,
                       error=type(exc).__name__)
            _RoutedFuture.chain(self, outer, inner, i, data, tenant,
                                offset, deadline)
            return True
        with self._lock:
            self.lost += 1
        self._emit("pool_lost", tenant=tenant, where="resubmit",
                   from_engine=int(engine))
        return False

    # -- family sync ----------------------------------------------------------

    def sync(self) -> bool:
        """Poll the store's generation stamp; on movement load the blob
        and fold the changed members into the pool's family (register +
        deploy).  Every engine's scorer re-snapshots on its next batch —
        recompile-free while the tenant bucket holds, and recompile-free
        across bucket growth too when the publisher prewarmed
        (serve/growth.py).  Returns whether anything changed."""
        if self.store is None:
            raise RuntimeError(f"pool {self.name!r} has no FamilyStore")
        gen = self.store.generation()
        if gen is None or gen == self._synced_generation:
            return False
        fresh = self.store.load()
        for t in fresh.tenants():
            dv = fresh.deployed_version(t)
            if t not in self.family.tenants():
                self.family.register(t, fresh.model(t, dv))
            elif not np.array_equal(
                    np.asarray(fresh.model(t, dv).coefficients),
                    np.asarray(self.family.model(t).coefficients)):
                self.family.register(t, fresh.model(t, dv), deploy=True)
        self._synced_generation = gen
        return True

    def prewarm_tenant_axis(self, n_tenants: int) -> tuple:
        """Warm every engine's scorer for a coming bucket crossing
        (serve/growth.py calls this through the growth coordinator when
        the pool's scorers are attached)."""
        return tuple(sc.prewarm_tenant_axis(n_tenants)
                     for sc in self.scorers)

    # -- lifecycle ------------------------------------------------------------

    def stats(self) -> dict:
        return dict(
            engines=self.n_engines,
            states=self.health.states(),
            ejections=self.health.ejections,
            recoveries=self.health.recoveries,
            resubmits=self.resubmits,
            lost=self.lost,
            compiles=[sc.compiles for sc in self.scorers],
            engine_health=[e.health.states() for e in self.engines])

    def close(self) -> None:
        for e in self.engines:
            e.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class _RoutedFuture:
    """Glue for the resubmit hook: an OUTER future the caller holds,
    chained to whatever INNER engine future currently backs it.  A
    terminal inner failure that looks like engine death re-routes once;
    every other outcome propagates."""

    _FATAL = (ReplicaUnavailable, RuntimeError)

    @classmethod
    def wrap(cls, pool, inner, engine, data, tenant, offset, deadline):
        from concurrent.futures import Future
        outer = Future()
        cls.chain(pool, outer, inner, engine, data, tenant, offset,
                  deadline)
        return outer

    @classmethod
    def chain(cls, pool, outer, inner, engine, data, tenant, offset,
              deadline) -> None:
        def done(f):
            exc = f.exception()
            if exc is None:
                if not outer.cancelled():
                    outer.set_result(f.result())
                return
            if isinstance(exc, cls._FATAL) and not outer.cancelled():
                if pool._resubmit(outer, exc, engine, data, tenant,
                                  offset, deadline):
                    return
            if not outer.cancelled():
                outer.set_exception(exc)
        inner.add_done_callback(done)
