"""Compiled-scorer cache: fixed-shape bucketed scoring for online serving.

The latency killer for JAX serving is recompilation: every distinct request
shape is a new executable, and XLA compiles in O(seconds) while a scoring
request wants O(milliseconds).  The fix is the same one the streaming fits
use for ragged tail chunks (``models/streaming.py::_bucket_pad``): quantize
request sizes to power-of-2 buckets, zero-pad up to the bucket, and slice
the outputs back.  Padded rows are INERT — every kernel output (eta, mu,
the se quadform) is row-local, so padding cannot perturb real rows — which
is what lets the same executable family serve every request size with
bit-identical results to an offline ``sg.predict`` (test-enforced;
PARITY.md).

A :class:`Scorer` wraps one fitted model:

  * requests arrive as raw column data (dicts of arrays — CSV-row shaped)
    and go through the model's own training ``Terms`` transform, the exact
    ``sg.predict`` path, including fit-time by-name offset recovery;
  * the design is padded to the nearest bucket and scored through the
    shared jit kernel (``models/scoring.py``), donating the padded buffer
    where the backend supports aliasing;
  * ``warmup(buckets=...)`` pre-compiles the executables so the first real
    request never pays XLA latency; after warmup, steady state is
    ZERO recompiles (``compiles`` counts them; bench.py proves the delta).

Because the kernel takes beta/vcov as runtime ARGUMENTS (not baked
constants), executables are shared across model versions with the same
signature: a registry ``deploy``/``rollback`` (serve/registry.py) is
recompile-free hot-swapping.
"""

from __future__ import annotations

import threading
import time
import zlib
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..data.frame import as_columns
from ..data.model_matrix import (structured_layout, transform,
                                 transform_structured, wants_structured)
from ..data.sparse import SparseDesign, SparseLayout
from ..data.structured import StructuredDesign
from ..models.scoring import (donation_supported, predict_sharded,
                              score_kernel_cache_size)
from ..obs.trace import emit_ambient

__all__ = ["FamilyScorer", "Scorer"]


def _next_bucket(n: int, floor: int) -> int:
    b = max(int(floor), 1)
    while b < n:
        b <<= 1
    return b


#: power-of-2 floor for the TENANT axis of family coefficient tables.
#: Every (T, p) table is zero-padded to ``tenant_bucket(T)`` rows before
#: it reaches the family kernel, so the compiled table shape is a
#: function of the tenant BUCKET, not the tenant count: registering new
#: tenants within the current bucket is shape-invariant and therefore
#: recompile-free, and crossing a bucket is an explicit, warmable event
#: (serve/growth.py).  Padded rows are inert — gather indices only ever
#: name real tenants, the same trash-row contract the request axis uses.
TENANT_BUCKET_FLOOR = 8


def tenant_bucket(n_tenants: int, floor: int = TENANT_BUCKET_FLOOR) -> int:
    """The power-of-2 tenant-axis bucket ``n_tenants`` pads to."""
    return _next_bucket(int(n_tenants), floor)


def pad_tenant_table(B: np.ndarray,
                     floor: int = TENANT_BUCKET_FLOOR) -> np.ndarray:
    """Zero-pad a (T, p) coefficient table to the tenant bucket (see
    :data:`TENANT_BUCKET_FLOOR`).  Returns ``B`` itself when T is
    already a bucket boundary."""
    T = int(B.shape[0])
    tb = tenant_bucket(T, floor)
    if tb == T:
        return B
    return np.concatenate([B, np.zeros((tb - T, B.shape[1]))])


class Scorer:
    """Pre-compiled bucketed scoring for ONE model (one (signature, bucket)
    executable per padding bucket; see module docstring).

    Args:
      model: a fitted ``LMModel``/``GLMModel`` (must carry ``terms`` to
        score raw column data; a bare (n, p) design is accepted too).
      type: "response" (GLM default, ignored for LM) or "link".
      se_fit: also return delta-method standard errors; requires the
        model's ``vcov()`` (resolved once, eagerly, so a model that cannot
        provide one fails at construction, not per-request).
      min_bucket: smallest padding bucket; buckets are min_bucket * 2^k.
      donate: donate the padded request buffer to the executable on
        backends that alias (TPU/GPU); silently off elsewhere.
      metrics: an ``obs.metrics.MetricsRegistry`` for per-model counters
        (``serve.<name>.requests/rows/compiles``) and the per-call
        ``serve.<name>.score_s`` latency histogram.
      name: metric namespace; defaults to the model class name.
    """

    def __init__(self, model, *, type: str = "response",
                 se_fit: bool = False, min_bucket: int = 8,
                 donate: bool = True, metrics=None, name: str | None = None):
        if type not in ("link", "response"):
            raise ValueError(
                f"type must be 'link' or 'response', got {type!r}")
        if min_bucket < 1:
            raise ValueError(f"min_bucket must be >= 1, got {min_bucket}")
        self.model = model
        self.is_glm = hasattr(model, "family")
        if self.is_glm:
            from ..families.links import get_link
            self._link = get_link(model.link)
        else:
            self._link = None  # LM: identity; type is irrelevant
        self.type = type
        self.se_fit = bool(se_fit)
        self._vcov = model.vcov() if se_fit else None
        self.min_bucket = int(min_bucket)
        self._donate = bool(donate) and donation_supported()
        self.metrics = metrics
        # NB: the ``type`` parameter shadows the builtin in this scope
        self.name = name if name is not None else model.__class__.__name__
        self.compiles = 0           # executables built on our behalf
        self.buckets = set()        # buckets seen (warmup + live)
        self._lock = threading.Lock()

    # -- design construction (the sg.predict contract) ----------------------

    def _design(self, data, offset):
        if isinstance(data, (StructuredDesign, SparseDesign)) or (
                isinstance(data, np.ndarray) and data.ndim == 2):
            X = data
            if X.shape[1] != self.model.n_params:
                raise ValueError(
                    f"design has {X.shape[1]} columns; model expects "
                    f"{self.model.n_params} (aligned to xnames)")
            return X, offset
        if self.model.terms is None:
            raise ValueError(
                "model was fit from arrays, not a formula; score with an "
                "aligned (n, p) design matrix instead of column data")
        cols = as_columns(data)
        # same predicate as sg.predict: wide-factor terms score through the
        # structured (segment/gather) representation, so served results stay
        # bit-identical to offline predictions
        X = (transform_structured(cols, self.model.terms)
             if wants_structured(self.model.terms)
             else transform(cols, self.model.terms))
        if offset is None:
            from ..api import _fit_time_offset
            offset = _fit_time_offset(self.model, cols)
        return X, offset

    # -- scoring ------------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        """The padding bucket an ``n``-row request runs in (next power of 2
        >= max(n, min_bucket))."""
        if n < 1:
            raise ValueError(f"request must have >= 1 row, got {n}")
        return _next_bucket(n, self.min_bucket)

    def score(self, data, *, offset=None):
        """Score one request; returns host ``fit`` or ``(fit, se)`` —
        bit-identical to ``sg.predict(model, data)`` with the same options.

        ``data``: dict of feature columns (goes through the training
        ``Terms``, recovering a fit-time by-name offset) or an aligned
        (n, p) design.  An explicit ``offset=`` overrides the stored one.
        """
        t0 = time.perf_counter()
        X, offset = self._design(data, offset)
        n = X.shape[0]
        bucket = self.bucket_for(n)
        with self._lock:
            before = score_kernel_cache_size()
            out = predict_sharded(
                X, self.model.coefficients, mesh=None, offset=offset,
                vcov=self._vcov, link=self._link,
                type=self.type if self.is_glm else "link",
                se_fit=self.se_fit, pad_to=bucket, donate=self._donate)
            compiled = score_kernel_cache_size() - before
            dt = time.perf_counter() - t0
            if compiled:
                self.compiles += compiled
                emit_ambient("compile", target=f"serve:{self.name}",
                             bucket=bucket, seconds=dt)
            self.buckets.add(bucket)
        if self.metrics is not None:
            self.metrics.counter(f"serve.{self.name}.requests").inc()
            self.metrics.counter(f"serve.{self.name}.rows").inc(n)
            if compiled:
                self.metrics.counter(
                    f"serve.{self.name}.compiles").inc(compiled)
            self.metrics.histogram(f"serve.{self.name}.score_s").observe(dt)
        return out

    def warmup(self, buckets=None, *,
               sparse_layout: SparseLayout | None = None) -> tuple[int, ...]:
        """Pre-compile the bucket executables so no real request pays XLA
        compile latency.  ``buckets=None`` compiles the power-of-2 ladder
        from ``min_bucket`` through 1024; pass the bucket sizes you expect
        (``bucket_for(n)`` maps request sizes to buckets) to warm a custom
        set.  Returns the buckets compiled, sorted.

        The warmed executable matches the live one exactly: same static
        flags (se_fit, response, offset-present) — a model fit with a
        by-name offset warms its offset-carrying variant.

        ``sparse_layout``: warm ``SparseDesign`` executables instead, for a
        model that will be scored with sparse requests (jit caches key on
        the layout, so the SAME ``SparseLayout`` the live requests carry
        must be passed — a model fit from a sparse design has no ``terms``
        to derive it from).  The warm rows are all-trash ELL rows (every
        slot column = n_sparse, value 0), inert by the double-guard
        convention.
        """
        if buckets is None:
            buckets, b = [], self.min_bucket
            while b <= 1024:
                buckets.append(b)
                b <<= 1
        p = self.model.n_params
        has_off = (getattr(self.model, "offset_col", None) is not None
                   or getattr(self.model, "has_offset", False))
        if sparse_layout is not None and sparse_layout.p != p:
            raise ValueError(
                f"sparse_layout has p={sparse_layout.p} columns; model "
                f"expects {p}")
        # warm the representation live requests will use: structured when
        # the terms want it (the se quadform runs structured too, via
        # ops/factor_gramian.structured_quadform)
        lay = (structured_layout(self.model.terms)
               if (self.model.terms is not None
                   and wants_structured(self.model.terms)) else None)
        done = []
        for b in sorted(set(int(x) for x in buckets)):
            if sparse_layout is not None:
                sl = sparse_layout
                X = SparseDesign(
                    np.zeros((1, sl.n_dense)),
                    np.full((1, sl.k), sl.n_sparse, np.int32),
                    np.zeros((1, sl.k)), sl)
            elif lay is not None:
                X = StructuredDesign(
                    np.zeros((1, lay.n_dense)),
                    tuple(np.full((1,), L, np.int32)
                          for _, L in lay.factors), lay)
            else:
                X = np.zeros((1, p))
            off = np.zeros(1) if has_off else None
            with self._lock:
                predict_sharded(
                    X, self.model.coefficients, mesh=None, offset=off,
                    vcov=self._vcov, link=self._link,
                    type=self.type if self.is_glm else "link",
                    se_fit=self.se_fit, pad_to=b, donate=self._donate)
                self.buckets.add(b)
            done.append(b)
        # warmup compiles are expected and paid up-front, so the counter
        # resets here: after warmup, ``compiles`` reads "steady-state
        # recompiles since warmup" — the number the SLO bench asserts is 0
        self.compiles = 0
        return tuple(done)


# -- family scoring: one dispatch for a mixed (tenant, x) batch ---------------

def _family_score_fn(X, tidx, arm, B, C, S, offset, *,
                     link, type, shadow, precision=None):
    """Gather-score a mixed-tenant request batch in one executable.

    ``B``/``C``/``S`` are stacked (T, p) coefficient tables (champion /
    challenger / shadow); ``tidx`` picks each request row's tenant,
    ``arm`` routes a row to the challenger table (A/B).  Every output is
    row-local, so bucket-padded trash rows are inert.  Tables are runtime
    ARGUMENTS — a family deploy/rollback swaps tables without recompiling.

    ``precision="bf16"`` (config.resolve_serve_precision) casts the eta
    einsum operands to bfloat16 with f32 accumulation — the opt-in
    reduced-precision serving tier (serve/async_engine.py; error bound in
    PARITY.md).  The default (None) einsum is untouched: that is the tier
    whose results are asserted bit-identical to offline scoring.
    """
    rows = jnp.where(arm[:, None], C[tidx], B[tidx])

    def eta_of(r):
        if precision == "bf16":
            e = jnp.einsum("np,np->n", X.astype(jnp.bfloat16),
                           r.astype(jnp.bfloat16),
                           preferred_element_type=jnp.float32)
            return e.astype(X.dtype) + offset
        return jnp.einsum("np,np->n", X, r) + offset

    def out(e):
        if type == "response" and link is not None:
            from ..families.links import get_link
            return get_link(link).inverse(e)
        return e

    if shadow:
        return out(eta_of(rows)), out(eta_of(S[tidx]))
    return out(eta_of(rows)), None


_FAMILY_STATICS = ("link", "type", "shadow", "precision")
_family_score_kernel = partial(
    jax.jit, static_argnames=_FAMILY_STATICS)(_family_score_fn)
# the replicated-serving steady-state variant: the padded batch buffer is
# built fresh per dispatch, so XLA may alias it with the output on backends
# that support donation (same HLO, same values — see models/scoring.py's
# donated twin; CPU callers gate on donation_supported()).
_family_score_kernel_donated = jax.jit(
    _family_score_fn, static_argnames=_FAMILY_STATICS, donate_argnums=(0,))


def family_score_cache_size() -> int:
    """Executables held across both family-kernel variants (compile-
    contract tests and bench.py count deltas of this)."""
    return int(_family_score_kernel._cache_size()
               + _family_score_kernel_donated._cache_size())


class FamilyScorer:
    """Batched serving for a :class:`~.registry.ModelFamily`: requests from
    MANY tenants score through one bucketed dispatch.

    At construction the scorer snapshots the family's deployed coefficient
    table (``deployed_matrix()``) and pins the family *generation* it came
    from; a later deploy/rollback does not mutate a live scorer — ask the
    family for a fresh one (``family.scorer()`` caches per generation).

    A/B and shadow deployments:

      * ``challenger={tenant: version}`` + ``ab_fraction``: requests for
        those tenants are deterministically split by ``keys=`` (stable
        request identity, e.g. user id) — a key hashes to the same arm
        forever, the standard sticky A/B contract.  Other tenants always
        serve the champion.
      * ``shadow={tenant: version}``: every request ALSO scores against
        the shadow table (champion rows except the overridden tenants) in
        the same dispatch; ``score`` returns ``(fit, shadow_fit)`` and
        only ``fit`` should be served.

    Args:
      family: the :class:`~.registry.ModelFamily` to snapshot.
      type: "response" (GLM default) or "link".
      min_bucket: smallest request padding bucket (power-of-2 ladder).
      challenger: ``{tenant: version}`` champion overrides for A/B.
      ab_fraction: challenger traffic share in [0, 1] (default 0.5).
      shadow: ``{tenant: version}`` overrides scored in shadow.
      metrics: ``obs.metrics.MetricsRegistry`` for request counters.
      name: metric namespace; defaults to the family name.
    """

    def __init__(self, family, *, type: str = "response",
                 min_bucket: int = 8, challenger: dict | None = None,
                 ab_fraction: float = 0.5, shadow: dict | None = None,
                 metrics=None, name: str | None = None):
        if type not in ("link", "response"):
            raise ValueError(
                f"type must be 'link' or 'response', got {type!r}")
        if min_bucket < 1:
            raise ValueError(f"min_bucket must be >= 1, got {min_bucket}")
        if not 0.0 <= float(ab_fraction) <= 1.0:
            raise ValueError(
                f"ab_fraction must be in [0, 1], got {ab_fraction}")
        self.family = family
        self.name = name if name is not None else family.name
        self.type = type
        self.min_bucket = int(min_bucket)
        self.ab_fraction = float(ab_fraction)
        self.metrics = metrics
        self.tenants, self._B = family.deployed_matrix()
        self._index = {t: i for i, t in enumerate(self.tenants)}
        self._link = family.link
        self.generation = family.generation()
        self._challenger = dict(challenger) if challenger else None
        self._C = self._override_table(self._challenger)
        self._shadow = dict(shadow) if shadow else None
        self._S = self._override_table(self._shadow)
        # tenant-axis bucket padding: table shapes key the compiled
        # executable, so padding to the tenant bucket makes every scorer
        # over <= bucket tenants share one executable family — tenant
        # growth within the bucket never recompiles (module helper doc)
        self._B = pad_tenant_table(self._B)
        self._C = pad_tenant_table(self._C)
        self._S = pad_tenant_table(self._S)
        self.compiles = 0
        self.buckets = set()
        self._lock = threading.Lock()

    def _override_table(self, overrides: dict | None) -> np.ndarray:
        """The champion table with ``{tenant: version}`` rows swapped in
        (versions resolve — and fail — at construction, not per request)."""
        table = self._B
        if overrides:
            table = self._B.copy()
            for tenant, version in overrides.items():
                i = self._index.get(str(tenant))
                if i is None:
                    raise KeyError(
                        f"override names unknown tenant {tenant!r}")
                table[i] = np.asarray(
                    self.family.model(str(tenant),
                                      int(version)).coefficients,
                    np.float64)
        return table

    # -- A/B routing ---------------------------------------------------------

    def assignments(self, tenants, keys) -> np.ndarray:
        """The deterministic challenger-arm mask ``score`` uses: True where
        a request serves the challenger.  Sticky per key — re-computable
        offline for experiment analysis."""
        tenants = np.atleast_1d(np.asarray(tenants, object))
        if self._challenger is None:
            return np.zeros(tenants.shape[0], bool)
        keys = np.atleast_1d(np.asarray(keys, object))
        in_ch = np.array([str(t) in self._challenger for t in tenants])
        cut = int(self.ab_fraction * 10_000)
        hashed = np.array([
            zlib.crc32(f"{self.name}:{k}".encode()) % 10_000 < cut
            for k in keys])
        return in_ch & hashed

    # -- scoring -------------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        if n < 1:
            raise ValueError(f"request must have >= 1 row, got {n}")
        return _next_bucket(n, self.min_bucket)

    def score(self, tenants, X, *, offset=None, keys=None):
        """Score a mixed-tenant batch in one dispatch.

        Args:
          tenants: per-row tenant labels (length n; a single label
            broadcasts over all rows).
          X: (n, p) design aligned to the family ``xnames``.
          offset: optional per-row offset added to eta.
          keys: stable per-request identities for A/B routing; REQUIRED
            when the scorer has a ``challenger``.

        Returns host ``fit`` — or ``(fit, shadow_fit)`` when the scorer
        carries a ``shadow`` table.
        """
        t0 = time.perf_counter()
        X = np.asarray(X, np.float64)
        if X.ndim != 2 or X.shape[1] != self._B.shape[1]:
            raise ValueError(
                f"design must be (n, {self._B.shape[1]}) aligned to the "
                f"family columns; got shape {X.shape}")
        n = X.shape[0]
        if isinstance(tenants, str):
            tenants = [tenants] * n
        tenants = np.asarray(tenants, object)
        if tenants.shape[0] != n:
            raise ValueError(
                f"{tenants.shape[0]} tenant labels for {n} design rows")
        try:
            tidx = np.array([self._index[str(t)] for t in tenants],
                            np.int32)
        except KeyError as exc:
            raise KeyError(
                f"{exc.args[0]!r} is not a tenant of family "
                f"{self.family.name!r}") from None
        if self._challenger is not None and keys is None:
            raise ValueError(
                "this scorer has a challenger A/B split; pass keys= "
                "(stable per-request identities) so arm assignment is "
                "deterministic and sticky")
        arm = self.assignments(tenants, keys)
        off = (np.zeros(n) if offset is None
               else np.asarray(offset, np.float64))
        bucket = self.bucket_for(n)
        pad = bucket - n
        Xp = np.concatenate([X, np.zeros((pad, X.shape[1]))]) if pad else X
        tp = np.concatenate([tidx, np.zeros(pad, np.int32)]) if pad else tidx
        ap = np.concatenate([arm, np.zeros(pad, bool)]) if pad else arm
        op = np.concatenate([off, np.zeros(pad)]) if pad else off
        with self._lock:
            before = family_score_cache_size()
            fit, sh = _family_score_kernel(
                Xp, tp, ap, self._B, self._C, self._S, op,
                link=self._link, type=self.type,
                shadow=self._shadow is not None)
            fit = np.asarray(fit)[:n]
            sh = None if sh is None else np.asarray(sh)[:n]
            compiled = family_score_cache_size() - before
            dt = time.perf_counter() - t0
            if compiled:
                self.compiles += compiled
                emit_ambient("compile", target=f"serve:{self.name}",
                             bucket=bucket, seconds=dt)
            self.buckets.add(bucket)
        if self.metrics is not None:
            self.metrics.counter(f"serve.{self.name}.requests").inc()
            self.metrics.counter(f"serve.{self.name}.rows").inc(n)
            if compiled:
                self.metrics.counter(
                    f"serve.{self.name}.compiles").inc(compiled)
            self.metrics.histogram(f"serve.{self.name}.score_s").observe(dt)
        # the kernel hop of whatever trace is ambient (an online refresh
        # cycle's shadow gating, a notebook fit) — host-side, after the
        # dispatch, so numerics and the executable census are untouched
        emit_ambient("scorer_kernel", target=f"serve:{self.name}",
                     rows=n, cols=int(self._B.shape[1]), bucket=bucket,
                     seconds=dt, shadow=self._shadow is not None)
        return fit if sh is None else (fit, sh)

    def warmup(self, buckets=None) -> tuple[int, ...]:
        """Pre-compile the bucket executables (power-of-2 ladder from
        ``min_bucket`` through 1024 by default) so no live request pays
        XLA compile latency; resets ``compiles`` to 0 afterwards."""
        if buckets is None:
            buckets, b = [], self.min_bucket
            while b <= 1024:
                buckets.append(b)
                b <<= 1
        p = self._B.shape[1]
        done = []
        for b in sorted(set(int(x) for x in buckets)):
            with self._lock:
                _family_score_kernel(
                    np.zeros((b, p)), np.zeros(b, np.int32),
                    np.zeros(b, bool), self._B, self._C, self._S,
                    np.zeros(b), link=self._link, type=self.type,
                    shadow=self._shadow is not None)
                self.buckets.add(b)
            done.append(b)
        self.compiles = 0
        return tuple(done)
