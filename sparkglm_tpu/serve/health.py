"""Per-replica health tracking for the self-healing serving plane.

A replicated engine (``AsyncEngine`` over a ``ReplicatedScorer``) routes
batches to whichever replica is free; without health tracking a hung or
failing replica keeps receiving its share of traffic and poisons every
request routed to it.  This module supplies the two pieces the engine
composes:

  * :class:`CircuitBreaker` — the classic typed breaker, one per replica:
    ``closed`` (traffic flows) → ``open`` after ``failure_threshold``
    consecutive failures (no traffic for ``cooldown_s``) → ``half_open``
    (exactly one probe call admitted) → ``closed`` again after
    ``probe_successes`` successful probes, or back to ``open`` with a
    fresh cooldown on a failed probe.  Probing is DETERMINISTIC: the
    transition to half-open happens on the first admission attempt after
    the cooldown elapses — no randomized probe scheduling — and because
    each replica index circulates at most once through the engine's free
    queue, at most one probe is ever in flight per replica by
    construction.

  * :class:`ReplicaHealth` — the engine-facing state machine over one
    breaker per replica, named in serving terms::

        healthy ──failure──▶ suspect ──failures──▶ ejected
           ▲                                          │ cooldown
           └────────── auto_recovery ◀── probing ◀────┘

    ``healthy``/``suspect`` map to a closed breaker (zero / nonzero
    consecutive failures), ``ejected`` to open, ``probing`` to half-open.
    Every transition emits a typed trace event (``replica_suspect``,
    ``replica_ejected``, ``replica_probe``, ``auto_recovery``) through the
    engine's emit hook; ``replica_ejected`` and ``auto_recovery`` are
    flight-recorder triggers (obs/slo.py), so an ejection episode dumps
    the event ring exactly like an SLO violation does.

GRACEFUL DEGRADATION INVARIANT: the LAST non-ejected replica is never
ejected, no matter how it fails — with R−1 (or even 0) healthy replicas
the engine must keep serving at reduced throughput rather than strand the
queue.  This is safe for correctness because scoring is replica-
independent (every replica holds a ``device_put`` copy of the same
coefficient tables and runs the same row-local kernel — see PARITY), so
which replica serves a batch never changes the bytes of the answer.

RE-WARM INVARIANT: a replica recovering through half-open sets a
``needs_rewarm`` flag that the engine's worker thread consumes
(:meth:`ReplicaHealth.take_rewarm`) BEFORE the probe batch is scored —
the replica's bucket ladder is re-driven through the scorer's warmup
(prepaid executables, see ``ReplicatedScorer.rewarm``), so recovery never
causes a steady-state compile.

:class:`HealthPolicy` bundles the knobs, including the two latency
budgets the engine's dispatch protection uses: ``call_timeout_s`` (the
watchdog deadline on each replica call — exceeded means the call is
abandoned as hung and the batch re-dispatched) and ``hedge_after_s`` (the
budget after which the SAME batch is speculatively re-dispatched to a
second free replica, first result wins).  Both default to ``None`` (off):
hedging and watchdogs are opt-in because they can double work.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Optional

__all__ = ["HealthPolicy", "CircuitBreaker", "ReplicaHealth"]


@dataclasses.dataclass(frozen=True)
class HealthPolicy:
    """Knobs for the per-replica health machinery.

    ``eject_after`` consecutive failures open a replica's breaker
    (ejection); after ``probe_cooldown_s`` it is probed half-open, and
    ``probe_successes`` clean probes re-admit it.  ``call_timeout_s`` is
    the per-call watchdog deadline (None = no watchdog);
    ``hedge_after_s`` the hedged-dispatch latency budget (None = no
    hedging).  ``max_attempts`` bounds scoring attempts per batch across
    re-dispatches and hedges — the guarantee "a batch is scored at most
    N times" that keeps tail amplification bounded.
    """

    eject_after: int = 3
    probe_cooldown_s: float = 0.25
    probe_successes: int = 1
    call_timeout_s: Optional[float] = None
    hedge_after_s: Optional[float] = None
    max_attempts: int = 2

    def __post_init__(self):
        if self.eject_after < 1:
            raise ValueError(f"eject_after must be >= 1, got {self.eject_after}")
        if self.probe_cooldown_s < 0:
            raise ValueError(
                f"probe_cooldown_s must be >= 0, got {self.probe_cooldown_s}")
        if self.probe_successes < 1:
            raise ValueError(
                f"probe_successes must be >= 1, got {self.probe_successes}")
        if self.call_timeout_s is not None and self.call_timeout_s <= 0:
            raise ValueError(
                f"call_timeout_s must be positive, got {self.call_timeout_s}")
        if self.hedge_after_s is not None and self.hedge_after_s <= 0:
            raise ValueError(
                f"hedge_after_s must be positive, got {self.hedge_after_s}")
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}")
        if (self.call_timeout_s is not None and self.hedge_after_s is not None
                and self.hedge_after_s >= self.call_timeout_s):
            raise ValueError(
                "hedge_after_s must be below call_timeout_s (a hedge that "
                "fires after the watchdog already declared the call hung "
                "would never run)")


class CircuitBreaker:
    """closed → open → half_open → closed, driven by call outcomes.

    Not thread-safe on its own — :class:`ReplicaHealth` serializes access;
    standalone users must too.  The clock is injectable so tests drive
    cooldowns deterministically without sleeping.
    """

    def __init__(self, *, failure_threshold: int = 3, cooldown_s: float = 0.25,
                 probe_successes: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self.probe_successes = int(probe_successes)
        self._clock = clock
        self.state = "closed"
        self.consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_ok = 0

    def record_success(self) -> str:
        if self.state == "half_open":
            self._probe_ok += 1
            if self._probe_ok >= self.probe_successes:
                self.state = "closed"
                self.consecutive_failures = 0
        else:
            self.consecutive_failures = 0
        return self.state

    def record_failure(self, *, allow_open: bool = True) -> str:
        """``allow_open=False`` is the last-replica guard: failures are
        counted but the breaker refuses to open (ejecting the only
        remaining replica would strand the queue entirely)."""
        self.consecutive_failures += 1
        if self.state == "half_open":
            # a failed probe re-opens immediately with a fresh cooldown
            self.state = "open" if allow_open else "closed"
            self._opened_at = self._clock()
            self._probe_ok = 0
        elif (self.state == "closed" and allow_open
                and self.consecutive_failures >= self.failure_threshold):
            self.state = "open"
            self._opened_at = self._clock()
            self._probe_ok = 0
        return self.state

    def remaining_cooldown(self, now: Optional[float] = None) -> float:
        if self.state != "open":
            return 0.0
        now = self._clock() if now is None else now
        return max(0.0, self.cooldown_s - (now - self._opened_at))

    def try_probe(self, now: Optional[float] = None) -> bool:
        """Deterministic half-open admission: the first attempt after the
        cooldown elapses flips open → half_open and is admitted; earlier
        attempts are refused.  Closed/half-open states always admit."""
        if self.state == "closed" or self.state == "half_open":
            return True
        if self.remaining_cooldown(now) > 0.0:
            return False
        self.state = "half_open"
        self._probe_ok = 0
        return True


_STATE_NAME = {"closed": "healthy", "open": "ejected", "half_open": "probing"}


class ReplicaHealth:
    """Health state for ``n_replicas`` replicas of one engine.

    ``emit`` is the engine's trace hook (``kind, **fields``); transitions
    emit through it.  Thread-safe: the engine's event-loop thread drives
    admissions/outcomes while worker threads consume re-warm flags.
    """

    def __init__(self, n_replicas: int, policy: Optional[HealthPolicy] = None,
                 *, emit: Optional[Callable] = None,
                 clock: Callable[[], float] = time.monotonic):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        self.policy = policy if policy is not None else HealthPolicy()
        self.n_replicas = int(n_replicas)
        self._emit = emit or (lambda kind, **fields: None)
        self._clock = clock
        self._lock = threading.Lock()
        self._breakers = [
            CircuitBreaker(failure_threshold=self.policy.eject_after,
                           cooldown_s=self.policy.probe_cooldown_s,
                           probe_successes=self.policy.probe_successes,
                           clock=clock)
            for _ in range(self.n_replicas)]
        self._needs_rewarm = [False] * self.n_replicas
        self.ejections = 0
        self.recoveries = 0

    # -- queries -------------------------------------------------------------

    @staticmethod
    def _name(b: CircuitBreaker) -> str:
        name = _STATE_NAME[b.state]
        if name == "healthy" and b.consecutive_failures > 0:
            name = "suspect"
        return name

    def state(self, replica: int) -> str:
        with self._lock:
            return self._name(self._breakers[replica])

    def states(self) -> dict:
        with self._lock:
            return {r: self._name(b) for r, b in enumerate(self._breakers)}

    def available(self) -> int:
        """Replicas currently admissible for dispatch (not ejected)."""
        with self._lock:
            return sum(1 for b in self._breakers if b.state != "open")

    # -- engine hooks --------------------------------------------------------

    def admit(self, replica: int) -> bool:
        """May this replica take a batch right now?  Flips ejected →
        probing (once, deterministically) when its cooldown has elapsed;
        the probing replica is flagged for re-warm before it scores."""
        with self._lock:
            b = self._breakers[replica]
            was_open = b.state == "open"
            ok = b.try_probe(self._clock())
            if ok and was_open:
                self._needs_rewarm[replica] = True
                self._emit("replica_probe", replica=int(replica))
            return ok

    def retry_delay(self, replica: int) -> float:
        """How long an ejected replica stays benched before the engine
        should offer it for admission again."""
        with self._lock:
            return self._breakers[replica].remaining_cooldown(self._clock())

    def on_success(self, replica: int) -> None:
        with self._lock:
            b = self._breakers[replica]
            was = b.state
            b.record_success()
            recovered = was == "half_open" and b.state == "closed"
            if recovered:
                self.recoveries += 1
                self._needs_rewarm[replica] = False
        if recovered:
            self._emit("auto_recovery", replica=int(replica),
                       probes=self.policy.probe_successes)

    def on_failure(self, replica: int, exc: BaseException) -> None:
        with self._lock:
            b = self._breakers[replica]
            was = b.state
            # never eject the last admissible replica: R−1 … 1 replicas
            # keep serving bit-identically at reduced throughput
            others = sum(1 for i, ob in enumerate(self._breakers)
                         if i != replica and ob.state != "open")
            now_state = b.record_failure(allow_open=others > 0)
            fails = b.consecutive_failures
            ejected = now_state == "open" and was != "open"
            suspect = (now_state == "closed" and fails == 1
                       and was == "closed")
            if ejected:
                self.ejections += 1
                self._needs_rewarm[replica] = False
        err = type(exc).__name__
        if suspect:
            self._emit("replica_suspect", replica=int(replica),
                       failures=fails, error=err)
        if ejected:
            self._emit("replica_ejected", replica=int(replica),
                       failures=fails, error=err,
                       probe_failed=was == "half_open",
                       cooldown_s=self.policy.probe_cooldown_s)

    def take_rewarm(self, replica: int) -> bool:
        """Consume the re-warm flag (set on ejected → probing).  Called by
        the worker thread that owns the probe batch, before scoring."""
        with self._lock:
            flag = self._needs_rewarm[replica]
            self._needs_rewarm[replica] = False
            return flag
