"""Async replicated serving: continuous batching over a mesh-replicated
scorer.

``MicroBatcher`` (serve/batching.py) proved the serving contracts —
coalescing is bit-neutral, backpressure is typed, errors deliver in order
— but it is a synchronous single-device loop: one scoring thread, one
device, one batch in flight.  This module is the scale-out half
(ROADMAP.md "planet-scale serving"; the parallel-and-stream decomposition
of arXiv 2111.00032 applied to the serve path: independent per-replica
compute, cheap combine at the edge):

:class:`ReplicatedScorer`
    replicates a model's (or a whole :class:`~.registry.ModelFamily`'s)
    coefficient tables onto every device of the mesh.  Tables are runtime
    kernel ARGUMENTS (the PR-9 design), so replication, deploys and
    rollbacks are all recompile-free: ``refresh()`` re-snapshots the
    family when its generation counter moved and ``device_put``s the new
    tables — same shapes, same executables, zero compiles.  Batches pack
    into the same power-of-2 buckets as every other scorer, with donated
    input buffers on backends that alias.  An opt-in reduced-precision
    tier (``precision="bf16"``, config.resolve_serve_precision) trades a
    documented max-abs-error bound (PARITY.md) for bf16 einsum operands;
    the default tier stays bit-identical to host ``model.predict``.

:class:`AsyncEngine`
    an asyncio continuous-batching front end over that scorer.  Admission
    is synchronous and typed — a full queue raises
    :class:`~..robust.retry.Overloaded` exactly like ``MicroBatcher`` —
    and admitted requests land in per-tenant FIFO queues.  A scheduler
    coroutine forms a fresh batch the moment a replica frees up
    (continuous batching: batch composition is decided at dispatch time,
    not admission time), packing rows across tenants by DEFICIT ROUND-
    ROBIN: each visit credits a tenant ``quantum`` rows and takes whole
    requests while credit lasts, so a flooding tenant cannot starve a
    light one — both make proportional progress at 2x capacity (test-
    enforced).  Batches dispatch to free replicas through a thread pool
    (one worker per replica), so every device scores concurrently.

``MicroBatcher`` itself is now a thin compatibility shim over this engine
(single tenant, single replica) — same API, same metric names, same
behavioural contracts, one scheduler implementation.

SELF-HEALING (serve/health.py): every replica carries a typed circuit
breaker driven by dispatch outcomes — consecutive failures move it
healthy → suspect → ejected, a deterministic half-open probe (after
``HealthPolicy.probe_cooldown_s``) moves it ejected → probing → healthy,
and a probing replica is RE-WARMED (its bucket ladder re-driven through
the scorer's prepaid executables) before it takes traffic again, so
recovery never causes a steady-state compile.  Dispatches are protected:
a batch whose replica call fails (or exceeds the ``call_timeout_s``
watchdog — the call is abandoned as hung, its late result discarded) is
re-dispatched to a surviving replica, and an optional ``hedge_after_s``
budget speculatively re-dispatches a slow batch to a second free replica
with first-result-wins semantics.  The last non-ejected replica is never
ejected: with R−1 (or 1) replicas the engine keeps serving bit-identically
at reduced throughput (scoring is replica-independent — every replica
holds the same ``device_put`` coefficient tables).  Requests accept a
``deadline=``; expired requests are SHED at batch-formation time (typed
:class:`~..robust.retry.DeadlineExceeded`) instead of burning replica
time, and ``score(timeout=)`` / ``asubmit(timeout=)`` cancel abandoned
requests out of the queue the same way.

Observability: the engine feeds ``serve.<name>.latency_s`` /
``rows_per_s`` / ``batches`` / ``batched_rows`` / ``overloaded`` (the
MicroBatcher names) plus ``queue_depth`` and ``batch_rows`` histograms
into its metrics registry, and emits ``admission`` (overload rejections),
``queue_depth`` and ``batch`` trace events through the ambient tracer
(obs/trace.py).

``telemetry=`` (an :class:`~..obs.export.Telemetry`) upgrades that to the
full runtime plane: every admitted request mints a DETERMINISTIC trace id
(per-engine submission counter — same seeded load, same ids) and emits a
typed span chain ``request_start -> queued -> batched -> dispatched ->
request_end`` carrying queue depth at enqueue, the DRR batch id, the
replica/bucket at dispatch, and queue_wait/latency at completion.  The
chain is seq-ordered PER REQUEST by construction: ``request_start`` and
``queued`` are emitted under the admission lock (before the scheduler can
see the request), ``batched`` on the scheduler thread before the dispatch
task is created, and ``dispatched``/``request_end`` on the worker — so
every chain is monotone in the tracer's sequence even though chains from
different requests interleave.  Per-tenant latency histograms
(``serve.<name>.tenant.<t>.latency_s``) feed the SLO engine, which is
evaluated (rate-limited) after every batch completion.  All of it is
host-side bookkeeping: traced serving is bit-identical to untraced and
compiles nothing extra (the serving_trace_overhead bench gate).
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeout

import jax
import numpy as np

from ..config import resolve_serve_precision
from ..data.frame import as_columns
from ..models.scoring import (donation_supported, predict_sharded,
                              score_kernel_cache_size)
from ..obs.trace import emit_ambient
from ..robust.retry import DeadlineExceeded, Overloaded, ReplicaUnavailable
from .engine import (Scorer, _family_score_kernel,
                     _family_score_kernel_donated, _next_bucket,
                     family_score_cache_size, pad_tenant_table,
                     tenant_bucket)
from .health import HealthPolicy, ReplicaHealth

__all__ = ["AsyncEngine", "EnginePolicy", "HealthPolicy", "ReplicatedScorer"]


# ---------------------------------------------------------------------------
# request coalescing helpers (moved here from batching.py; the shim re-uses
# them through this module)
# ---------------------------------------------------------------------------

def _signature(data, offset) -> tuple:
    """Only identically-shaped requests coalesce: same feature columns (or
    same design width) and same explicit-offset-ness.  Model-side offset
    recovery is per-column-name, hence covered by the column signature."""
    if isinstance(data, np.ndarray):
        return ("design", data.shape[1], offset is not None)
    return ("cols",) + tuple(sorted(data)) + (offset is not None,)


def _merge(batch):
    """Concatenate member requests into one scoring call's input."""
    first = batch[0]
    if len(batch) == 1:
        return first.data, first.offset
    if isinstance(first.data, np.ndarray):
        data = np.concatenate([r.data for r in batch], axis=0)
    else:
        data = {k: np.concatenate([np.asarray(r.data[k]) for r in batch])
                for k in first.data}
    off = (np.concatenate([np.asarray(r.offset, np.float64) for r in batch])
           if first.offset is not None else None)
    return data, off


def _split(res, sizes):
    """Slice a batch result back into per-request results (handles the
    se_fit ``(fit, se)`` tuple shape)."""
    edges = np.cumsum([0] + list(sizes))
    if isinstance(res, tuple):
        return [tuple(part[edges[i]:edges[i + 1]] for part in res)
                for i in range(len(sizes))]
    return [res[edges[i]:edges[i + 1]] for i in range(len(sizes))]


# ---------------------------------------------------------------------------
# ReplicatedScorer
# ---------------------------------------------------------------------------

class ReplicatedScorer:
    """Coefficient tables replicated across the device mesh, one bucketed
    executable family per replica.

    ``target`` is either a :class:`~.registry.ModelFamily` (family mode:
    mixed-tenant gather batches through the family kernel) or one fitted
    model (model mode: the ``predict_sharded`` path — the executable
    family host ``predict`` shares, which is what keeps default-tier
    serving bit-identical to ``model.predict``).

    Replication/refresh are recompile-free by construction: tables are
    runtime kernel arguments, so ``refresh()`` after a family deploy or
    rollback just ``device_put``s the new (T, p) snapshot to every
    replica.  Tables are padded to the power-of-2 TENANT bucket
    (``engine.pad_tenant_table``), so growing the tenant set within the
    bucket is shape-invariant and recompile-free too; growth that
    crosses a bucket changes shapes and honestly recompiles (counted in
    ``compiles``) unless the next bucket was prewarmed first
    (:meth:`prewarm_tenant_axis` — the serve/growth.py warm phase).

    A/B challenger and shadow tables are deliberately not replicated —
    experiment traffic routes through :class:`~.engine.FamilyScorer`; the
    replicated path serves the champion tier at maximum throughput.

    Args:
      target: a ``ModelFamily`` or a fitted model.
      devices: the replica devices (default: every ``jax.devices()``).
      type: "response" (GLM default) or "link".
      se_fit: delta-method standard errors (model mode only).
      min_bucket: smallest padding bucket (power-of-2 ladder).
      precision: ``None``/"default" (bit-identical tier) or "bf16"
        (reduced-precision eta; config.resolve_serve_precision).
      donate: donate padded batch buffers on backends that alias.
      metrics: ``obs.metrics.MetricsRegistry`` for per-scorer counters.
      name: metric namespace; defaults to the family/model name.
    """

    def __init__(self, target, *, devices=None, type: str = "response",
                 se_fit: bool = False, min_bucket: int = 8,
                 precision: str | None = None, donate: bool = True,
                 metrics=None, name: str | None = None):
        if type not in ("link", "response"):
            raise ValueError(
                f"type must be 'link' or 'response', got {type!r}")
        if min_bucket < 1:
            raise ValueError(f"min_bucket must be >= 1, got {min_bucket}")
        self.devices = (tuple(devices) if devices is not None
                        else tuple(jax.devices()))
        if not self.devices:
            raise ValueError("need at least one replica device")
        self.n_replicas = len(self.devices)
        self.precision = resolve_serve_precision(precision)
        self.type = type
        self.min_bucket = int(min_bucket)
        self.metrics = metrics
        self._donate = bool(donate) and donation_supported()
        self.family_mode = hasattr(target, "deployed_matrix")
        self.compiles = 0
        self.buckets = set()
        self._warmed = set()        # (replica, bucket, flavor) fast paths
        self._lock = threading.Lock()
        if self.family_mode:
            if se_fit:
                raise ValueError(
                    "se_fit is not supported for family serving (no "
                    "per-tenant vcov table); serve a single model instead")
            self.family = target
            self.model = None
            self.name = name if name is not None else target.name
            self._link = target.link
            self.generation = -1
            self.refresh()
        else:
            self.family = None
            self.model = target
            if self.precision == "bf16" and se_fit:
                raise ValueError("the bf16 tier has no se_fit variant")
            # compose a Scorer for its design-construction contract (the
            # sg.predict path: Terms transform + by-name offset recovery)
            self._base = Scorer(target, type=type, se_fit=se_fit,
                                donate=False, min_bucket=min_bucket)
            self.name = name if name is not None else self._base.name
            self.generation = 0
            if self.precision == "bf16":
                # bf16 model serving routes through the family kernel with
                # a one-row table (tidx all zero)
                B1 = np.nan_to_num(np.asarray(
                    target.coefficients, np.float64))[None, :]
                self._link = target.link if self._base.is_glm else None
                self._tables = [jax.device_put(B1, d) for d in self.devices]

    # -- family snapshot / refresh -------------------------------------------

    def refresh(self) -> bool:
        """Re-snapshot the family's deployed tables if its generation
        moved since the last snapshot; ``device_put`` them to every
        replica.  Same tenant set -> same shapes -> ZERO recompiles (the
        engine calls this before every family batch).  Returns whether a
        new snapshot was taken."""
        if not self.family_mode:
            return False
        if self.family.generation() == self.generation:
            return False
        with self._lock:
            gen = self.family.generation()
            if gen == self.generation:
                return False
            tenants, B = self.family.deployed_matrix()
            # tenant-axis bucket padding (engine.pad_tenant_table): the
            # compiled executable keys on the TABLE shape, so padding to
            # the tenant bucket makes growth within the bucket
            # shape-invariant — refresh() after such a growth re-uses
            # every warm executable, zero recompiles
            B = pad_tenant_table(B)
            if getattr(self, "_B", None) is not None \
                    and B.shape != self._B.shape:
                self._warmed.clear()    # tenant BUCKET crossed: new shapes
            self.tenants = tenants
            self._index = {t: i for i, t in enumerate(tenants)}
            self._B = B
            self._tables = [jax.device_put(B, d) for d in self.devices]
            self.generation = gen
        if self.metrics is not None:
            self.metrics.counter(f"serve.{self.name}.refreshes").inc()
        return True

    def tenant_indices(self, tenants) -> np.ndarray:
        """Resolve tenant labels to gather indices for the CURRENT
        snapshot (the engine resolves at dispatch time, so a refresh
        between admission and dispatch stays correct)."""
        try:
            return np.array([self._index[str(t)] for t in tenants],
                            np.int32)
        except KeyError as exc:
            raise KeyError(
                f"{exc.args[0]!r} is not a tenant of family "
                f"{self.family.name!r}") from None

    # -- scoring -------------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        if n < 1:
            raise ValueError(f"request must have >= 1 row, got {n}")
        return _next_bucket(n, self.min_bucket)

    def _counted(self, key, size_fn, call):
        """Run ``call``; on the first visit of (replica, bucket, flavor)
        measure the executable-cache delta so ``compiles`` keeps the
        steady-state-recompile contract per replica."""
        if key in self._warmed:
            return call()
        with self._lock:
            before = size_fn()
            t0 = time.perf_counter()
            out = call()
            delta = size_fn() - before
            if delta:
                self.compiles += delta
                emit_ambient("compile", target=f"serve:{self.name}",
                             bucket=key[1], flavor=key[2],
                             seconds=time.perf_counter() - t0)
                if self.metrics is not None:
                    self.metrics.counter(
                        f"serve.{self.name}.compiles").inc(delta)
            self._warmed.add(key)
        return out

    def _family_call(self, Xp, tp, op, bucket, replica, table=None):
        d = self.devices[replica]
        kern = (_family_score_kernel_donated if self._donate
                else _family_score_kernel)
        B = self._tables[replica] if table is None else table
        Xd = jax.device_put(Xp, d)
        td = jax.device_put(tp, d)
        ad = jax.device_put(np.zeros(bucket, bool), d)
        od = jax.device_put(op, d)
        fit, _ = kern(Xd, td, ad, B, B, B, od, link=self._link,
                      type=self.type, shadow=False,
                      precision=self.precision)
        return fit

    def score_family(self, tenants, X, *, offset=None, replica: int = 0):
        """Score a mixed-tenant batch on one replica (family mode).

        ``tenants``: per-row gather indices (np.int32, from
        :meth:`tenant_indices`) or per-row tenant labels.  ``X``: (n, p)
        design aligned to the family xnames.
        """
        if not self.family_mode:
            raise RuntimeError(
                "score_family() needs a ModelFamily target; this scorer "
                "replicates a single model — use score()")
        t0 = time.perf_counter()
        X = np.asarray(X, np.float64)
        if X.ndim != 2 or X.shape[1] != self._B.shape[1]:
            raise ValueError(
                f"design must be (n, {self._B.shape[1]}) aligned to the "
                f"family columns; got shape {X.shape}")
        n = X.shape[0]
        tenants = np.asarray(tenants)
        if tenants.shape[0] != n:
            raise ValueError(
                f"{tenants.shape[0]} tenant labels for {n} design rows")
        tidx = (tenants.astype(np.int32)
                if np.issubdtype(tenants.dtype, np.integer)
                else self.tenant_indices(tenants))
        off = (np.zeros(n) if offset is None
               else np.asarray(offset, np.float64))
        bucket = self.bucket_for(n)
        pad = bucket - n
        Xp = np.concatenate([X, np.zeros((pad, X.shape[1]))]) if pad else X
        tp = np.concatenate([tidx, np.zeros(pad, np.int32)]) if pad else tidx
        op = np.concatenate([off, np.zeros(pad)]) if pad else off
        replica = int(replica) % self.n_replicas
        fit = self._counted(
            (replica, bucket, "family"), family_score_cache_size,
            lambda: self._family_call(Xp, tp, op, bucket, replica))
        out = np.asarray(fit)[:n]
        self.buckets.add(bucket)
        self._observe(n, time.perf_counter() - t0)
        return out

    def score(self, data, *, offset=None, replica: int = 0):
        """Score one request on one replica (model mode) — default tier
        results are bit-identical to ``model.predict`` (PARITY.md).

        ``data``: dict of feature columns (training-``Terms`` transform,
        fit-time by-name offset recovery) or an aligned (n, p) design.
        """
        if self.family_mode:
            raise RuntimeError(
                "score() needs a single-model target; this scorer "
                "replicates a ModelFamily — use score_family()")
        t0 = time.perf_counter()
        X, offset = self._base._design(data, offset)
        n = X.shape[0]
        bucket = self.bucket_for(n)
        replica = int(replica) % self.n_replicas
        if self.precision == "bf16":
            if not isinstance(X, np.ndarray):
                raise ValueError(
                    "the bf16 tier scores dense designs only; structured/"
                    "sparse requests need the default precision tier")
            X = np.asarray(X, np.float64)
            off = (np.zeros(n) if offset is None
                   else np.asarray(offset, np.float64))
            pad = bucket - n
            Xp = (np.concatenate([X, np.zeros((pad, X.shape[1]))])
                  if pad else X)
            tp = np.zeros(bucket, np.int32)
            op = np.concatenate([off, np.zeros(pad)]) if pad else off
            fit = self._counted(
                (replica, bucket, "bf16"), family_score_cache_size,
                lambda: self._family_call(Xp, tp, op, bucket, replica))
            out = np.asarray(fit)[:n]
        else:
            out = self._counted(
                (replica, bucket, offset is not None),
                score_kernel_cache_size,
                lambda: predict_sharded(
                    X, self.model.coefficients, mesh=None, offset=offset,
                    vcov=self._base._vcov, link=self._base._link,
                    type=self.type if self._base.is_glm else "link",
                    se_fit=self._base.se_fit, pad_to=bucket,
                    donate=self._donate, device=self.devices[replica]))
        self.buckets.add(bucket)
        self._observe(n, time.perf_counter() - t0)
        return out

    def _observe(self, n, dt):
        if self.metrics is not None:
            self.metrics.counter(f"serve.{self.name}.requests").inc()
            self.metrics.counter(f"serve.{self.name}.rows").inc(n)
            self.metrics.histogram(f"serve.{self.name}.score_s").observe(dt)

    def _warm_one(self, r: int, b: int) -> None:
        """Drive one (replica, bucket) executable through ``_counted`` —
        the shared probe call under :meth:`warmup` and :meth:`rewarm`."""
        if self.family_mode:
            p = self._B.shape[1]
            self._counted(
                (r, b, "family"), family_score_cache_size,
                lambda b=b, r=r: self._family_call(
                    np.zeros((b, p)), np.zeros(b, np.int32),
                    np.zeros(b), b, r))
        elif self.precision == "bf16":
            p = self.model.n_params
            self._counted(
                (r, b, "bf16"), family_score_cache_size,
                lambda b=b, r=r: self._family_call(
                    np.zeros((b, p)), np.zeros(b, np.int32),
                    np.zeros(b), b, r))
        else:
            p = self.model.n_params
            has_off = (getattr(self.model, "offset_col", None)
                       is not None
                       or getattr(self.model, "has_offset", False))
            off = np.zeros(1) if has_off else None
            self._counted(
                (r, b, has_off), score_kernel_cache_size,
                lambda b=b, r=r, off=off: predict_sharded(
                    np.zeros((1, p)), self.model.coefficients,
                    mesh=None, offset=off, vcov=self._base._vcov,
                    link=self._base._link,
                    type=self.type if self._base.is_glm else "link",
                    se_fit=self._base.se_fit, pad_to=b,
                    donate=self._donate,
                    device=self.devices[r]))

    def warmup(self, buckets=None) -> tuple[int, ...]:
        """Pre-compile every (replica, bucket) executable — replicas
        compile independently, so warmup cost scales with the mesh — then
        reset ``compiles`` to 0: afterwards it reads "steady-state
        recompiles since warmup", the number the scale-out bench asserts
        is 0 across deploys and rollbacks."""
        if buckets is None:
            buckets, b = [], self.min_bucket
            while b <= 1024:
                buckets.append(b)
                b <<= 1
        done = []
        for b in sorted(set(int(x) for x in buckets)):
            for r in range(self.n_replicas):
                self._warm_one(r, b)
            self.buckets.add(b)
            done.append(b)
        self.compiles = 0
        return tuple(done)

    def prewarm_tenant_axis(self, n_tenants: int, *, buckets=None) -> dict:
        """Background-compile the family executables for the tenant
        bucket ``n_tenants`` will land in, BEFORE the family grows
        (serve/growth.py's warm phase).  Drives the family kernel with a
        zero coefficient table of ``tenant_bucket(n_tenants)`` rows over
        every (replica, request-bucket) this scorer serves, so when the
        swap crosses the bucket boundary the post-swap :meth:`refresh`
        finds every executable already in the process-wide jit cache —
        the hot path pays zero compiles.  Compiles are reported HERE,
        never added to ``compiles`` (the steady-state counter): growth
        warming is off the serving path by construction.  No-op when
        ``n_tenants`` stays within the current table bucket."""
        if not self.family_mode:
            raise RuntimeError(
                "tenant-axis prewarm needs a ModelFamily target")
        tb = tenant_bucket(int(n_tenants))
        p = self._B.shape[1]
        if tb <= self._B.shape[0]:
            return dict(table_rows=int(self._B.shape[0]), buckets=0,
                        compiles=0, seconds=0.0)
        bks = sorted(set(int(b) for b in (
            self.buckets if buckets is None else buckets)))
        if not bks:
            bks = [self.min_bucket]
        before = family_score_cache_size()
        t0 = time.perf_counter()
        for r in range(self.n_replicas):
            table = jax.device_put(np.zeros((tb, p)), self.devices[r])
            for b in bks:
                self._family_call(np.zeros((b, p)), np.zeros(b, np.int32),
                                  np.zeros(b), b, r, table=table)
        return dict(table_rows=tb, buckets=len(bks),
                    compiles=int(family_score_cache_size() - before),
                    seconds=time.perf_counter() - t0)

    def rewarm(self, replica: int) -> dict:
        """Prepay ONE replica's bucket ladder before it is re-admitted
        after an ejection (serve/health.py recovery path): drive every
        bucket this scorer has served — including buckets that first
        appeared WHILE the replica was ejected — through the probe call.
        Already-warm (replica, bucket) pairs cost one cached dispatch;
        new pairs compile here, on the probe, instead of on the first
        user batch after re-admission.  Returns the buckets driven and
        the compile delta (0 in steady state — executables survive an
        ejection because the jit cache is process-wide and the tables
        stay ``device_put``; the recovery contract the chaos bench
        asserts)."""
        replica = int(replica) % self.n_replicas
        before = self.compiles
        driven = []
        for b in sorted(self.buckets):
            driven.append(int(b))
            self._warm_one(replica, b)
        return dict(buckets=len(driven),
                    compiles=int(self.compiles - before))


# ---------------------------------------------------------------------------
# AsyncEngine
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class EnginePolicy:
    """Continuous-batching knobs.

    ``max_batch``: row cap per dispatch (one kernel call); a single
    request larger than this still runs, alone.  ``max_wait_ms``: how
    long a freshly-admitted request may wait for company before a batch
    MUST form (0 = dispatch the moment a replica frees up — continuous
    batching proper; MicroBatcher compatibility maps ``max_delay_ms``
    here).  ``max_queue``: admitted-request cap beyond which ``submit``
    raises :class:`Overloaded`.  ``max_queue_rows``: optional admitted-ROW
    cap (requests vary in size; this bounds memory).  ``quantum``: rows
    credited per tenant per deficit-round-robin visit — the fairness
    granularity."""

    max_batch: int = 1024
    max_wait_ms: float = 0.0
    max_queue: int = 4096
    max_queue_rows: int | None = None
    quantum: int = 256

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.max_wait_ms < 0:
            raise ValueError(
                f"max_wait_ms must be >= 0, got {self.max_wait_ms}")
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {self.max_queue}")
        if self.max_queue_rows is not None and self.max_queue_rows < 1:
            raise ValueError(
                f"max_queue_rows must be >= 1, got {self.max_queue_rows}")
        if self.quantum < 1:
            raise ValueError(f"quantum must be >= 1, got {self.quantum}")


@dataclasses.dataclass
class _Pending:
    tenant: str
    data: object          # (n, p) design (family) / design-or-columns (model)
    offset: object
    n: int
    key: tuple            # coalescing signature
    future: Future
    t_submit: float
    trace: str = ""       # deterministic request trace id (telemetry mode)
    deadline: float = 0.0  # absolute perf_counter deadline; 0.0 = none


_DEFAULT_TENANT = "_"


class AsyncEngine:
    """Asyncio continuous batching over a (replicated) scorer.

    ``submit`` is thread-safe and synchronous: admission control runs in
    the caller's thread (a full queue raises :class:`Overloaded` — typed,
    transient, retryable) and returns a ``concurrent.futures.Future``.
    ``asubmit`` is the awaitable twin for asyncio callers.  The scheduler
    coroutine runs on a dedicated event-loop thread; batches form at
    dispatch time under deficit round-robin and score on free replicas
    through a one-worker-per-replica thread pool.

    Works over a :class:`ReplicatedScorer` (family or model mode) or any
    duck-typed scorer with ``score(data, *, offset=None)`` (one replica).

    Use as a context manager or call ``close()``: pending requests drain
    before the loop exits (MicroBatcher semantics), and any request the
    scheduler could not serve is failed — never orphaned.

    ``health=`` (a :class:`~.health.HealthPolicy`) configures the
    self-healing plane: per-replica circuit breakers, the watchdog
    deadline, the hedged-dispatch budget.  The default policy keeps
    ejection/probing on and watchdog/hedging off.  ``fault_plan=`` (a
    :class:`~..robust.faults.FaultPlan`) injects seeded serving faults at
    dispatch time — the chaos-test hook.
    """

    def __init__(self, scorer, policy: EnginePolicy | None = None, *,
                 metrics=None, name: str | None = None, telemetry=None,
                 health: HealthPolicy | None = None, fault_plan=None):
        self.scorer = scorer
        self.policy = policy if policy is not None else EnginePolicy()
        # explicit metrics= wins; then the telemetry registry (so SLO
        # evaluation reads the engine's own instruments); then the scorer's
        self.metrics = (metrics if metrics is not None
                        else telemetry.metrics if telemetry is not None
                        else getattr(scorer, "metrics", None))
        self.name = name if name is not None else getattr(
            scorer, "name", scorer.__class__.__name__)
        self.telemetry = telemetry
        self._tracer = telemetry.tracer if telemetry is not None else None
        if telemetry is not None:
            telemetry.watch_engine(self.name)
        self._submitted = 0       # request trace ids (under _lock)
        self._batches_formed = 0  # batch ids (under _lock)
        self.family_mode = bool(getattr(scorer, "family_mode", False))
        self.n_replicas = int(getattr(scorer, "n_replicas", 1))
        self._routes_replica = isinstance(scorer, ReplicatedScorer)
        self._lock = threading.Lock()
        self._queues: dict[str, collections.deque] = {}
        self._active: collections.deque[str] = collections.deque()
        self._deficit: dict[str, int] = {}
        self._queued_reqs = 0
        self._queued_rows = 0
        self._closed = False
        self._inflight = 0            # loop-thread only
        self._rows_done = 0           # worker threads, under _lock
        self._t_first = None
        self._shed = 0                # deadline-shed requests, under _lock
        self._has_deadlines = False   # any queued req with deadline (lock)
        self._abandoned = 0           # hung replica calls left running
        # future -> replica index to recycle when the hung call returns,
        # or None if the index was recycled at abandonment (loop thread)
        self._abandoned_calls: dict = {}
        self._abandoned_recycled = 0  # calls with a recycled index (loop)
        self._hedges = 0              # loop-thread only
        self._redispatches = 0        # loop-thread only
        self._fault_plan = fault_plan
        self.health = ReplicaHealth(self.n_replicas, health,
                                    emit=self._emit)
        # Slack workers: a watchdog-abandoned (hung) call keeps its
        # worker until it returns; slack lets the recycled replica index
        # take new work meanwhile.  Concurrency per replica is still 1 in
        # the steady state — each index circulates once through _free.
        # At most _abandon_slack abandoned calls get their index recycled
        # immediately; past that bound the hung call HOLDS its index
        # until it returns (released in _call), so occupied workers never
        # exceed n_replicas + slack and re-dispatches never queue behind
        # hung workers.
        self._abandon_slack = self.n_replicas + 2
        self._pool = ThreadPoolExecutor(
            max_workers=self.n_replicas + self._abandon_slack,
            thread_name_prefix=f"serve-replica:{self.name}")
        self._loop = asyncio.new_event_loop()
        self._started = threading.Event()
        self._thread = threading.Thread(
            target=self._run_loop, daemon=True,
            name=f"async-engine:{self.name}")
        self._thread.start()
        self._started.wait()

    def _emit(self, kind: str, **fields) -> None:
        """Telemetry tracer when attached, else the ambient tracer — the
        one emission path for every engine event."""
        if self._tracer is not None:
            self._tracer.emit(kind, **fields)
        else:
            emit_ambient(kind, **fields)

    def _scorer_cols(self) -> int | None:
        """The coefficient-table width p, stamped on ``scorer_kernel``
        events so the capacity observatory (obs/profile.py) can price a
        dispatch as a ``bucket x p`` gather-matvec.  Host-side metadata
        only."""
        B = getattr(self.scorer, "_B", None)
        if B is not None:
            return int(B.shape[1])
        m = getattr(self.scorer, "model", None)
        coef = getattr(m, "coefficients", None)
        return int(len(coef)) if coef is not None else None

    # -- client side ---------------------------------------------------------

    def submit(self, data, *, tenant: str | None = None,
               offset=None, deadline: float | None = None) -> Future:
        """Admit one scoring request; returns its Future immediately.

        Family mode: ``data`` is an (n, p) design aligned to the family
        xnames and ``tenant`` is REQUIRED (one tenant per request — the
        fairness unit; batches mix tenants).  Model mode: ``data`` is
        column data or an aligned design, ``tenant`` is an optional
        fairness key.

        ``deadline=`` (seconds from now): a request still queued when its
        deadline passes is SHED at batch-formation time — its future
        fails with :class:`~..robust.retry.DeadlineExceeded` and no
        replica time is spent on it.  A request already dispatched when
        the deadline passes completes normally (the deadline bounds
        queue wait, not kernel time).

        Raises :class:`Overloaded` when ``policy.max_queue`` requests (or
        ``max_queue_rows`` rows) are already waiting — carrying a
        ``retry_after_s`` drain-rate hint — and ``RuntimeError`` after
        ``close()``.
        """
        return self._admit(data, tenant=tenant, offset=offset,
                           deadline=deadline).future

    def _admit(self, data, *, tenant: str | None = None, offset=None,
               deadline: float | None = None) -> _Pending:
        if self.family_mode:
            if tenant is None:
                raise ValueError(
                    "family serving needs tenant= on every request")
            data = np.asarray(data, np.float64)
            if data.ndim != 2:
                raise ValueError(
                    f"design requests must be 2-D, got shape {data.shape}")
            n = data.shape[0]
            key = ("family", data.shape[1], offset is not None)
        else:
            if isinstance(data, np.ndarray):
                if data.ndim != 2:
                    raise ValueError(
                        f"design requests must be 2-D, got shape "
                        f"{data.shape}")
                n = data.shape[0]
            else:
                data = as_columns(data)
                n = (len(np.asarray(next(iter(data.values()))))
                     if data else 0)
            key = _signature(data, offset)
        if n < 1:
            raise ValueError("request must have >= 1 row")
        if deadline is not None and deadline <= 0:
            raise ValueError(f"deadline must be positive, got {deadline}")
        tenant = str(tenant) if tenant is not None else _DEFAULT_TENANT
        now = time.perf_counter()
        req = _Pending(tenant=tenant, data=data, offset=offset, n=n,
                       key=key, future=Future(), t_submit=now,
                       deadline=(now + deadline) if deadline else 0.0)
        pol = self.policy
        with self._lock:
            if self._closed:
                raise RuntimeError(f"AsyncEngine {self.name!r} is closed")
            if (self._queued_reqs >= pol.max_queue
                    or (pol.max_queue_rows is not None
                        and self._queued_rows + n > pol.max_queue_rows)):
                if self.metrics is not None:
                    self.metrics.counter(
                        f"serve.{self.name}.overloaded").inc()
                retry_after = None
                if self._t_first is not None:
                    elapsed = now - self._t_first
                    rate = self._rows_done / elapsed if elapsed > 0 else 0.0
                    if rate > 0:
                        # how long until the measured drain rate clears
                        # what is queued ahead of a retry
                        retry_after = min(
                            max(self._queued_rows / rate, 1e-3), 60.0)
                self._emit("admission", engine=self.name, tenant=tenant,
                           outcome="overloaded",
                           queued_requests=self._queued_reqs,
                           queued_rows=self._queued_rows,
                           retry_after_s=retry_after)
                raise Overloaded(
                    f"serving queue for {self.name!r} is full "
                    f"({self._queued_reqs} requests / {self._queued_rows} "
                    "rows waiting); retry with backoff",
                    retry_after_s=retry_after)
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = collections.deque()
                self._active.append(tenant)
                self._deficit.setdefault(tenant, 0)
            q.append(req)
            self._queued_reqs += 1
            self._queued_rows += n
            if req.deadline:
                self._has_deadlines = True
            if self._tracer is not None:
                # mint + emit UNDER the admission lock: the scheduler can
                # only see this request after we release, so its `batched`
                # event sequences strictly after these two — every
                # request's span chain is monotone in tracer seq
                self._submitted += 1
                req.trace = f"req-{self.name}-{self._submitted:08d}"
                self._tracer.emit("request_start", trace=req.trace,
                                  engine=self.name, tenant=tenant,
                                  rows=n)
                self._tracer.emit("queued", trace=req.trace, tenant=tenant,
                                  queued_requests=self._queued_reqs,
                                  queued_rows=self._queued_rows)
        try:
            self._loop.call_soon_threadsafe(self._notify)
        except RuntimeError:
            pass  # close() raced us; the drain loop already saw the request
        return req

    async def asubmit(self, data, *, tenant: str | None = None,
                      offset=None, deadline: float | None = None,
                      timeout: float | None = None):
        """Awaitable ``submit`` for asyncio callers.

        ``timeout=`` bounds the whole wait AND cancels a still-queued
        request out of the queue on expiry (it is never dispatched — no
        dead-work leak), raising :class:`~..robust.retry.DeadlineExceeded`.
        A request that is already mid-dispatch completes on the replica,
        but its result is discarded and the timeout still raises."""
        eff = deadline
        if timeout is not None:
            if timeout <= 0:
                raise ValueError(f"timeout must be positive, got {timeout}")
            eff = timeout if eff is None else min(eff, timeout)
        req = self._admit(data, tenant=tenant, offset=offset, deadline=eff)
        fut = asyncio.wrap_future(req.future)
        if timeout is None:
            return await fut
        try:
            return await asyncio.wait_for(fut, timeout)
        except (asyncio.TimeoutError, TimeoutError):
            self._cancel_queued(req, reason="timeout")
            raise DeadlineExceeded(
                f"request to {self.name!r} timed out after {timeout}s and "
                "was cancelled out of the queue") from None

    def score(self, data, *, tenant: str | None = None, offset=None,
              timeout: float | None = None, deadline: float | None = None):
        """Blocking submit: the served result (or the served exception).

        On ``timeout=`` expiry the request is cancelled out of the queue
        (never dispatched) and :class:`~..robust.retry.DeadlineExceeded`
        raises — a timed-out caller leaves no dead work behind."""
        req = self._admit(data, tenant=tenant, offset=offset,
                          deadline=deadline)
        try:
            return req.future.result(timeout)
        except (TimeoutError, FuturesTimeout):
            if req.future.done():
                raise  # the SERVED outcome was DeadlineExceeded — re-raise
            self._cancel_queued(req, reason="timeout")
            raise DeadlineExceeded(
                f"request to {self.name!r} timed out after {timeout}s and "
                "was cancelled out of the queue") from None

    def _cancel_queued(self, req: _Pending, *, reason: str) -> bool:
        """Remove an abandoned request from its tenant queue (if it is
        still there) and fail its future.  Returns whether THIS call
        settled the request; False means it was already dispatched (its
        in-flight result will be discarded by the abandoned future)."""
        with self._lock:
            q = self._queues.get(req.tenant)
            removed = False
            if q is not None:
                try:
                    q.remove(req)
                    removed = True
                except ValueError:
                    pass
            if removed:
                self._queued_reqs -= 1
                self._queued_rows -= req.n
                self._shed += 1
                if not q:
                    if req.tenant in self._active:
                        self._active.remove(req.tenant)
                    self._deficit.pop(req.tenant, None)
                    self._queues.pop(req.tenant, None)
        if not removed:
            return False
        exc = DeadlineExceeded(
            f"request to {self.name!r} abandoned while queued ({reason})")
        settled = self._settle(req.future, exc=exc)
        if settled:
            self._shed_bookkeeping(req, reason)
        return settled

    def _shed_bookkeeping(self, req: _Pending, reason: str) -> None:
        if self.metrics is not None:
            self.metrics.counter(f"serve.{self.name}.shed").inc()
        f = dict(engine=self.name, tenant=req.tenant, rows=req.n,
                 reason=reason,
                 waited_s=time.perf_counter() - req.t_submit)
        if req.trace:
            f["trace"] = req.trace
        self._emit("deadline_shed", **f)

    @staticmethod
    def _settle(fut: Future, value=None, exc=None) -> bool:
        """First-result-wins completion: hedged dispatches may both try
        to finish a request; only one wins, the loser is discarded."""
        try:
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(value)
            return True
        except Exception:
            return False  # already settled (hedge loser / cancelled)

    def close(self) -> None:
        """Drain pending requests, then stop the scheduler loop.

        Never orphans a future: requests the scheduler could not serve
        (it died, or a replica call is permanently hung) are failed with
        ``RuntimeError`` after the loop thread exits.  The worker pool is
        joined only when no abandoned (hung) call is still running —
        a hung replica call cannot block shutdown."""
        with self._lock:
            if self._closed:
                if self._thread.is_alive():
                    self._thread.join()
                return
            self._closed = True
        try:
            self._loop.call_soon_threadsafe(self._notify)
        except RuntimeError:
            pass  # loop already dead; the sweep below still runs
        self._thread.join()
        with self._lock:
            leftovers = []
            for q in self._queues.values():
                leftovers.extend(q)
                q.clear()
            self._queues.clear()
            self._active.clear()
            self._deficit.clear()
            self._queued_reqs = 0
            self._queued_rows = 0
        if leftovers:
            exc = RuntimeError(
                f"AsyncEngine {self.name!r} closed before this request "
                "could be dispatched")
            for r in leftovers:
                if self._settle(r.future, exc=exc):
                    self._note_error(r, None, -1, exc)
        self._pool.shutdown(wait=self._abandoned == 0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- scheduler (event-loop thread) ---------------------------------------

    def _notify(self) -> None:
        self._wake.set()

    def _run_loop(self) -> None:
        asyncio.set_event_loop(self._loop)
        self._wake = asyncio.Event()
        self._free: asyncio.Queue = asyncio.Queue()
        for r in range(self.n_replicas):
            self._free.put_nowait(r)
        self._started.set()
        try:
            self._loop.run_until_complete(self._scheduler())
        finally:
            self._loop.close()

    async def _scheduler(self) -> None:
        replica = None
        while True:
            if replica is None:
                replica = await self._acquire()
            action, val = self._next_action()
            if action == "batch":
                if self._tracer is not None:
                    # emitted BEFORE the dispatch task exists, so
                    # `batched` sequences before the worker's
                    # `dispatched` for every member request
                    batch, _, _, batch_id = val
                    for r in batch:
                        self._tracer.emit("batched", trace=r.trace,
                                          tenant=r.tenant,
                                          batch=batch_id, rows=r.n)
                self._inflight += 1
                asyncio.ensure_future(self._dispatch(replica, val))
                replica = None
                continue
            if action == "exit":
                return
            # idle: release the held replica while we sleep, so hedges,
            # re-dispatches and recovery probes can use it meanwhile
            self._free.put_nowait(replica)
            replica = None
            self._wake.clear()
            # no await between _next_action and clear(): _notify runs on
            # this thread, so a wakeup cannot be lost in between
            if action == "wait":
                try:
                    await asyncio.wait_for(self._wake.wait(),
                                           timeout=max(val, 1e-4))
                except asyncio.TimeoutError:
                    pass
            else:
                await self._wake.wait()

    async def _acquire(self):
        """Next replica admissible for dispatch.  Ejected replicas coming
        off the free queue are benched — re-offered by timer once their
        breaker cooldown elapses (the deterministic half-open probe
        schedule); :meth:`ReplicaHealth.admit` flips them to probing."""
        while True:
            r = await self._free.get()
            if self.health.admit(r):
                return r
            delay = max(self.health.retry_delay(r), 1e-3)
            self._loop.call_later(delay, self._free.put_nowait, r)

    def _drain_free(self, *, exclude):
        """Pop every immediately-free replica; return (usable, skipped):
        the first admissible replica not in ``exclude`` (or None) and the
        replicas to put back."""
        skipped, got = [], None
        while got is None:
            try:
                r = self._free.get_nowait()
            except asyncio.QueueEmpty:
                break
            if r in exclude or not self.health.admit(r):
                skipped.append(r)
            else:
                got = r
        return got, skipped

    def _try_acquire_now(self, exclude):
        """Non-blocking acquisition for hedged dispatch: an admissible
        replica not yet tried for this batch, or None (no hedge — never
        wait for one; the primary may still win)."""
        got, skipped = self._drain_free(exclude=exclude)
        for s in skipped:
            self._free.put_nowait(s)
        return got

    async def _acquire_retry(self, tried):
        """Blocking acquisition for re-dispatch after a replica failure:
        wait for an admissible replica this batch has NOT been tried on.
        Returns None when no such replica can exist (every replica
        tried).  Already-tried replicas are held out of circulation only
        while we wait and always returned; an untried replica that fails
        admission (mid-cooldown) is re-offered by timer exactly as
        :meth:`_acquire` does — holding it here would leave the free
        queue empty with no pending wakeup and deadlock this wait."""
        if len(set(tried)) >= self.n_replicas:
            return None

        def bench(r):
            self._loop.call_later(max(self.health.retry_delay(r), 1e-3),
                                  self._free.put_nowait, r)

        held = []
        try:
            while True:
                got, skipped = self._drain_free(exclude=tried)
                for s in skipped:
                    (held.append if s in tried else bench)(s)
                if got is not None:
                    return got
                r = await self._free.get()
                if r in tried:
                    held.append(r)
                elif self.health.admit(r):
                    return r
                else:
                    bench(r)
        finally:
            for s in held:
                self._free.put_nowait(s)

    def _call(self, loop, replica, payload):
        """One replica call as an asyncio future.  The replica index
        recirculates when ITS call finishes — not when the logical batch
        completes — unless the call was abandoned by the watchdog (the
        index was already recycled then)."""
        fut = loop.run_in_executor(
            self._pool, self._run_batch, replica, payload)

        def _release(f):
            try:
                f.exception()       # consume; _protected handles outcomes
            except BaseException:
                pass
            if f in self._abandoned_calls:
                rep = self._abandoned_calls.pop(f)
                self._abandoned -= 1    # the hung call finally returned
                if rep is None:         # index was recycled at abandonment
                    self._abandoned_recycled -= 1
                    return
                # index was held past the abandonment bound — release now
            self._free.put_nowait(replica)
            self._wake.set()

        fut.add_done_callback(_release)
        return fut

    async def _dispatch(self, replica, payload) -> None:
        try:
            await self._protected(replica, payload)
        finally:
            self._inflight -= 1
            self._wake.set()

    async def _protected(self, replica, payload) -> None:
        """Run one batch with failure protection: watchdog abandonment of
        hung calls, re-dispatch to a surviving replica on failure, hedged
        speculative dispatch past the latency budget.  First result wins;
        a batch's futures fail only when every attempt (bounded by
        ``HealthPolicy.max_attempts``) is exhausted."""
        batch, _, _, batch_id = payload
        pol = self.health.policy
        loop = asyncio.get_running_loop()
        calls: dict = {}
        deadlines: dict = {}   # per-CALL watchdog: launch + call_timeout_s
        tried: list = []
        attempts = 0
        last_exc = None

        def launch(r):
            nonlocal attempts
            attempts += 1
            tried.append(r)
            f = self._call(loop, r, payload)
            calls[f] = r
            if pol.call_timeout_s is not None:
                deadlines[f] = loop.time() + pol.call_timeout_s

        def redispatch(error):
            self._redispatches += 1
            if self.metrics is not None:
                self.metrics.counter(
                    f"serve.{self.name}.redispatches").inc()
            f = dict(engine=self.name, replica=int(nxt),
                     failed_replica=int(tried[-1]), error=error,
                     rows=sum(r.n for r in batch))
            if batch_id is not None:
                f["batch"] = batch_id
            self._emit("redispatch", **f)
            launch(nxt)

        launch(replica)
        start = loop.time()
        hedged = False
        while calls:
            timeout = None
            if (not hedged and pol.hedge_after_s is not None
                    and attempts < pol.max_attempts
                    and self.n_replicas > 1):
                timeout = max(0.0, start + pol.hedge_after_s - loop.time())
            if deadlines:
                rem = max(0.0, min(deadlines.values()) - loop.time())
                timeout = rem if timeout is None else min(timeout, rem)
            done, _ = await asyncio.wait(
                set(calls), timeout=timeout,
                return_when=asyncio.FIRST_COMPLETED)
            if done:
                success = False
                for f in done:
                    rep = calls.pop(f)
                    deadlines.pop(f, None)
                    exc = f.exception()
                    if exc is None:
                        self.health.on_success(rep)
                        success = True
                    else:
                        last_exc = exc
                        self.health.on_failure(rep, exc)
                if success:
                    return  # a still-pending hedge loses by first-wins
                if calls:
                    continue  # a hedge is still in flight — it may win
                if attempts < pol.max_attempts:
                    nxt = await self._acquire_retry(tried)
                    if nxt is not None:
                        redispatch(type(last_exc).__name__)
                        continue
                self._fail_batch(batch, last_exc, batch_id, tried[-1])
                return
            now = loop.time()
            expired = [f for f, dl in deadlines.items() if now >= dl]
            if expired:
                # each call is judged against ITS OWN deadline — a hedge
                # launched at start+hedge_after_s gets a full
                # call_timeout_s of runtime, not the primary's leftovers.
                # Abandon the hung call (the worker keeps running; its
                # late result is discarded by first-wins).  Its replica
                # index is recycled immediately while no more than
                # _abandon_slack abandoned calls are running — past that
                # the index stays held until the call returns, so new
                # dispatches cannot queue behind hung workers.
                for f in expired:
                    rep = calls.pop(f)
                    del deadlines[f]
                    exc = ReplicaUnavailable(
                        f"replica {rep} of {self.name!r} exceeded the "
                        f"{pol.call_timeout_s}s watchdog deadline")
                    last_exc = exc
                    self.health.on_failure(rep, exc)
                    recycle = self._abandoned_recycled < self._abandon_slack
                    self._abandoned_calls[f] = None if recycle else rep
                    self._abandoned += 1
                    if recycle:
                        self._abandoned_recycled += 1
                        self._free.put_nowait(rep)
                        self._wake.set()
                    fl = dict(engine=self.name, replica=int(rep),
                              deadline_s=pol.call_timeout_s,
                              index_held=not recycle)
                    if batch_id is not None:
                        fl["batch"] = batch_id
                    self._emit("replica_hung", **fl)
                if calls:
                    continue  # a hedge with a later deadline may still win
                if attempts < pol.max_attempts:
                    nxt = await self._acquire_retry(tried)
                    if nxt is not None:
                        redispatch("watchdog_timeout")
                        continue
                self._fail_batch(batch, last_exc, batch_id, tried[-1])
                return
            if (not hedged and pol.hedge_after_s is not None
                    and attempts < pol.max_attempts and self.n_replicas > 1
                    and now >= start + pol.hedge_after_s):
                hedged = True
                nxt = self._try_acquire_now(tried)
                if nxt is not None:
                    self._hedges += 1
                    if self.metrics is not None:
                        self.metrics.counter(
                            f"serve.{self.name}.hedges").inc()
                    f = dict(engine=self.name, primary=int(replica),
                             hedge=int(nxt), after_s=pol.hedge_after_s,
                             rows=sum(r.n for r in batch))
                    if batch_id is not None:
                        f["batch"] = batch_id
                    self._emit("hedge_dispatch", **f)
                    launch(nxt)

    def _fail_batch(self, batch, exc, batch_id, replica) -> None:
        """Terminal failure: every attempt exhausted — deliver the last
        error to each member future (first-wins guarded)."""
        if exc is None:
            exc = ReplicaUnavailable(
                f"no replica of {self.name!r} could serve this batch")
        for r in batch:
            if self._settle(r.future, exc=exc):
                self._note_error(r, batch_id, replica, exc)
        if self.telemetry is not None:
            self.telemetry.evaluate_slos()

    def _next_action(self):
        """One scheduling decision: ('batch', payload) | ('wait', s) |
        ('idle', None) | ('exit', None)."""
        pol = self.policy
        with self._lock:
            self._shed_expired_locked()
            if self._queued_reqs == 0:
                if self._closed and self._inflight == 0:
                    return "exit", None
                return "idle", None
            if not self._closed and pol.max_wait_ms > 0 \
                    and self._queued_rows < pol.max_batch:
                oldest = min(q[0].t_submit
                             for q in self._queues.values() if q)
                remaining = (oldest + pol.max_wait_ms / 1e3
                             - time.perf_counter())
                if remaining > 0:
                    return "wait", remaining
            batch = self._form_batch_locked()
            if not batch:
                return "idle", None   # defensive; force-take prevents this
            self._batches_formed += 1
            batch_id = (f"batch-{self.name}-{self._batches_formed:06d}"
                        if self._tracer is not None else None)
            return "batch", (batch, self._queued_reqs, self._queued_rows,
                             batch_id)

    def _shed_expired_locked(self) -> None:
        """Dead-work shedding at batch-formation time (caller holds the
        lock): drop every queued request whose deadline already passed,
        failing its future with :class:`DeadlineExceeded` — a caller that
        gave up never costs replica time.  O(queued) but skipped entirely
        while no queued request carries a deadline."""
        if not self._has_deadlines:
            return
        now = time.perf_counter()
        shed, still = [], False
        for t in list(self._queues):
            q = self._queues[t]
            expired = [r for r in q if r.deadline and now > r.deadline]
            if expired:
                kept = [r for r in q if not (r.deadline and now > r.deadline)]
                q.clear()
                q.extend(kept)
                shed.extend(expired)
            still = still or any(r.deadline for r in q)
            if not q:
                if t in self._active:
                    self._active.remove(t)
                self._deficit.pop(t, None)
                self._queues.pop(t, None)
        self._has_deadlines = still
        for r in shed:
            self._queued_reqs -= 1
            self._queued_rows -= r.n
            self._shed += 1
            exc = DeadlineExceeded(
                f"request to {self.name!r} exceeded its deadline after "
                f"{now - r.t_submit:.3f}s in queue; shed before dispatch")
            if self._settle(r.future, exc=exc):
                self._shed_bookkeeping(r, "deadline")

    def _form_batch_locked(self):
        """Deficit round-robin batch formation (caller holds the lock).

        Each visited tenant earns ``quantum`` rows of credit and
        contributes whole requests (per-tenant FIFO, never reordered)
        while credit and batch row-room last; only same-signature
        requests share a batch.  A tenant whose queue empties leaves the
        rotation and forfeits its credit (classic DRR — no hoarding).
        Rounds repeat until the batch fills or a full round adds nothing
        — so a lone tenant still fills ``max_batch`` while contending
        tenants split each batch ~proportionally.  If the FIRST round
        yields nothing (every head over-credit or signature-incompatible),
        the head of the longest-waiting tenant is force-taken so progress
        is guaranteed.
        """
        pol = self.policy
        batch, rows, key = [], 0, None
        while rows < pol.max_batch:
            progressed = False
            for _ in range(len(self._active)):
                t = self._active[0]
                q = self._queues.get(t)
                if not q:
                    self._active.popleft()
                    self._deficit.pop(t, None)
                    self._queues.pop(t, None)
                    continue
                self._deficit[t] = self._deficit.get(t, 0) + pol.quantum
                while q and rows < pol.max_batch:
                    head = q[0]
                    if key is not None and head.key != key:
                        break
                    if head.n > self._deficit[t]:
                        break
                    if batch and rows + head.n > pol.max_batch:
                        break
                    q.popleft()
                    if key is None:
                        key = head.key
                    batch.append(head)
                    rows += head.n
                    progressed = True
                    self._deficit[t] -= head.n
                    self._queued_reqs -= 1
                    self._queued_rows -= head.n
                if not q:
                    self._active.popleft()
                    self._deficit.pop(t, None)
                    self._queues.pop(t, None)
                else:
                    self._active.rotate(-1)
                if rows >= pol.max_batch:
                    break
            if not progressed:
                break
        if not batch and self._queued_reqs:
            # force-take the longest-waiting head: guarantees progress
            # for requests larger than any accumulated quantum
            t = min((t for t, q in self._queues.items() if q),
                    key=lambda t: self._queues[t][0].t_submit)
            q = self._queues[t]
            head = q.popleft()
            self._deficit[t] = 0
            batch.append(head)
            self._queued_reqs -= 1
            self._queued_rows -= head.n
            if not q:
                if t in self._active:
                    self._active.remove(t)
                self._deficit.pop(t, None)
                self._queues.pop(t, None)
        return batch

    # -- batch execution (replica worker threads) ----------------------------

    def _run_batch(self, replica, payload) -> None:
        batch, depth_reqs, depth_rows, batch_id = payload
        rows = sum(r.n for r in batch)
        bucket = (self.scorer.bucket_for(rows)
                  if hasattr(self.scorer, "bucket_for") and rows else rows)
        if self._tracer is not None:
            for r in batch:
                self._tracer.emit("dispatched", trace=r.trace,
                                  tenant=r.tenant, batch=batch_id,
                                  replica=int(replica), bucket=int(bucket))
        t0 = time.perf_counter()
        # batch-level failures below (a scorer/device error, an injected
        # fault, a failed re-warm) PROPAGATE through the executor future to
        # the dispatch coordinator (_protected), which re-dispatches to a
        # surviving replica or fails the futures once attempts exhaust —
        # errors here no longer reach request futures directly
        if self.health.take_rewarm(replica):
            self._rewarm(replica)
        if self._fault_plan is not None:
            self._fault_plan.on_dispatch(replica)
        if self.family_mode:
            self.scorer.refresh()
            # resolve per request so an unknown tenant fails ITS
            # future without poisoning the rest of the batch
            idx, live = [], []
            for r in batch:
                try:
                    idx.append(int(
                        self.scorer.tenant_indices([r.tenant])[0]))
                    live.append(r)
                except KeyError as e:
                    if self._settle(r.future, exc=e):
                        self._note_error(r, batch_id, replica, e)
            batch = live
            if not batch:
                return
            rows = sum(r.n for r in batch)
            tidx = np.repeat(np.array(idx, np.int32),
                             [r.n for r in batch])
            X = (np.concatenate([r.data for r in batch])
                 if len(batch) > 1 else batch[0].data)
            if batch[0].offset is not None:
                off = np.concatenate(
                    [np.asarray(r.offset, np.float64) for r in batch])
            else:
                off = None
            res = self.scorer.score_family(tidx, X, offset=off,
                                           replica=replica)
        else:
            data, off = _merge(batch)
            if self._routes_replica:
                res = self.scorer.score(data, offset=off,
                                        replica=replica)
            else:
                res = self.scorer.score(data, offset=off)
        parts = _split(res, [r.n for r in batch])
        now = time.perf_counter()
        dt = now - t0
        # first-result-wins: under hedging two replicas may finish the
        # same batch; only the requests THIS call settles get bookkeeping
        won = [(r, part) for r, part in zip(batch, parts)
               if self._settle(r.future, part)]
        if not won:
            return  # hedge loser — the other replica delivered everything
        with self._lock:
            if self._t_first is None:
                self._t_first = now
            self._rows_done += rows
            done, t_first = self._rows_done, self._t_first
        if self._tracer is not None:
            # the kernel hop of every member request's trace (batch-scoped:
            # requests share the executable call)
            self._tracer.emit("scorer_kernel", engine=self.name,
                              batch=batch_id, replica=int(replica),
                              bucket=int(bucket), rows=rows,
                              cols=self._scorer_cols(), seconds=dt)
        for r, _part in won:
            if self.metrics is not None:
                self.metrics.histogram(
                    f"serve.{self.name}.latency_s").observe(
                        now - r.t_submit)
            if self._tracer is not None:
                self._tracer.emit(
                    "request_end", trace=r.trace, tenant=r.tenant,
                    batch=batch_id, replica=int(replica),
                    bucket=int(bucket), rows=r.n,
                    queue_wait=t0 - r.t_submit, seconds=now - r.t_submit)
                if self.metrics is not None:
                    self.metrics.histogram(
                        f"serve.{self.name}.tenant.{r.tenant}.latency_s"
                    ).observe(now - r.t_submit)
        self._emit("queue_depth", engine=self.name,
                   requests=depth_reqs, rows=depth_rows)
        f = dict(engine=self.name, rows=rows, requests=len(won),
                 replica=int(replica),
                 tenants=len({r.tenant for r, _ in won}), seconds=dt)
        if batch_id is not None:
            f["batch"] = batch_id
        self._emit("batch", **f)
        if self.metrics is not None:
            m = self.metrics
            m.counter(f"serve.{self.name}.batches").inc()
            m.counter(f"serve.{self.name}.batched_rows").inc(rows)
            m.counter(f"serve.{self.name}.requests_done").inc(len(won))
            m.histogram(f"serve.{self.name}.batch_rows").observe(rows)
            m.histogram(f"serve.{self.name}.queue_depth").observe(
                depth_reqs)
            elapsed = now - t_first
            if elapsed > 0:
                m.gauge(f"serve.{self.name}.rows_per_s").set(done / elapsed)
        if self.telemetry is not None:
            # rate-limited: one real evaluation per interval regardless of
            # batch rate (obs/slo.py)
            self.telemetry.evaluate_slos()

    def _rewarm(self, replica) -> None:
        """Prepay a recovering replica's bucket ladder before its probe
        batch scores (scorers without ``rewarm`` skip — duck scorers have
        no bucketed executables to warm)."""
        fn = getattr(self.scorer, "rewarm", None)
        if fn is None:
            return
        t0 = time.perf_counter()
        info = fn(replica)
        self._emit("replica_rewarm", engine=self.name, replica=int(replica),
                   seconds=time.perf_counter() - t0,
                   **(info if isinstance(info, dict) else {}))

    def _note_error(self, r, batch_id, replica, exc) -> None:
        """Error-path bookkeeping for one failed request (its future is
        already failed by the caller)."""
        if self.metrics is not None:
            self.metrics.counter(f"serve.{self.name}.errors").inc()
        if self._tracer is not None:
            self._tracer.emit("request_end", trace=r.trace, tenant=r.tenant,
                              batch=batch_id, replica=int(replica),
                              outcome="error", error=type(exc).__name__,
                              seconds=time.perf_counter() - r.t_submit)
