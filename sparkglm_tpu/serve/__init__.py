"""Online serving: model registry, compiled-scorer cache, micro-batching.

The training side of this repo answers "fit a GLM on more data than fits";
this package answers the other half of the production loop: "score requests
against the fitted model in milliseconds, forever".  Three pieces:

  * :class:`~.registry.ModelRegistry` — versioned in-process model store
    with ``register``/``load``/``deploy``/``rollback``; every version
    carries its training ``Terms`` so raw feature dicts score through the
    exact training transform.
  * :class:`~.engine.Scorer` — the compiled-scorer cache: one donated-
    buffer executable per (model signature, padding bucket); requests pad
    to the nearest power-of-2 bucket (inert rows), so steady-state serving
    NEVER recompiles.  ``warmup()`` pre-pays every compile.
  * :class:`~.registry.ModelFamily` / :class:`~.engine.FamilyScorer` —
    the fleet-serving pair: per-tenant versioned deploy/rollback over ONE
    shared design signature, scored as mixed ``(tenant, x)`` batches in a
    single gather-score dispatch (with sticky A/B splits and shadow
    scoring in the same executable).
  * :class:`~.async_engine.AsyncEngine` / :class:`~.async_engine.
    ReplicatedScorer` — the scale-out pair: coefficient tables replicated
    across the device mesh, fed by an asyncio continuous-batching
    scheduler with per-tenant deficit-round-robin fairness, typed
    :class:`~..robust.retry.Overloaded` backpressure, and an opt-in
    reduced-precision tier (``precision="bf16"``).  Deploys/rollbacks
    refresh replicas recompile-free (tables are runtime kernel args).
  * :class:`~.batching.MicroBatcher` — the original micro-batching API,
    now a thin compatibility shim over the engine (single tenant, single
    replica): bounded admission coalescing requests under a latency
    budget (``BatchPolicy``), same metrics, same contracts.

Serving is numerics-NEUTRAL: a served prediction (default precision tier)
is bit-identical to ``sg.predict`` on the same rows (PARITY.md;
test-enforced across every padding bucket), because serving runs the same
jitted kernel as offline scoring and every kernel output is row-local.

Self-healing (:mod:`.health`): per-replica circuit breakers with
deterministic half-open probing drive a healthy → suspect → ejected →
probing → healthy state machine over the engine's replicas; failed or
hung dispatches re-route to surviving replicas (R−1 serving stays
bit-identical — same tables, same kernel), recovered replicas re-warm
their bucket ladder before re-admission, and per-request deadlines shed
dead work at batch-formation time (README "Failure semantics").

Elastic tenancy (:mod:`.growth`, :mod:`.pool`): coefficient tables pad
to a power-of-2 TENANT bucket, so registering tenants within the bucket
is recompile-free by shape-invariance; :class:`~.growth.FamilyGrowth`
sequences bucket-crossing growth as warm-then-swap (prewarm the next
bucket's executables off the hot path, then one generation bump) so the
serving path never recompiles and never drops a request.
:class:`~.pool.EnginePool` runs N engines over one family with
engine-level health routing and :class:`~.pool.FamilyStore`
generation-stamped cross-process publication (README "Scaling the
tenant axis").
"""

from .async_engine import AsyncEngine, EnginePolicy, ReplicatedScorer
from .batching import BatchPolicy, MicroBatcher
from .engine import (FamilyScorer, Scorer, family_score_cache_size,
                     pad_tenant_table, tenant_bucket)
from .growth import FamilyGrowth
from .health import CircuitBreaker, HealthPolicy, ReplicaHealth
from .pool import EnginePool, FamilyStore
from .registry import ModelFamily, ModelRegistry

__all__ = ["AsyncEngine", "BatchPolicy", "CircuitBreaker", "EnginePolicy",
           "EnginePool", "FamilyGrowth", "FamilyScorer", "FamilyStore",
           "HealthPolicy", "MicroBatcher", "ModelFamily", "ModelRegistry",
           "ReplicaHealth", "ReplicatedScorer", "Scorer",
           "family_score_cache_size", "pad_tenant_table", "tenant_bucket"]
