"""Online serving: model registry, compiled-scorer cache, micro-batching.

The training side of this repo answers "fit a GLM on more data than fits";
this package answers the other half of the production loop: "score requests
against the fitted model in milliseconds, forever".  Three pieces:

  * :class:`~.registry.ModelRegistry` — versioned in-process model store
    with ``register``/``load``/``deploy``/``rollback``; every version
    carries its training ``Terms`` so raw feature dicts score through the
    exact training transform.
  * :class:`~.engine.Scorer` — the compiled-scorer cache: one donated-
    buffer executable per (model signature, padding bucket); requests pad
    to the nearest power-of-2 bucket (inert rows), so steady-state serving
    NEVER recompiles.  ``warmup()`` pre-pays every compile.
  * :class:`~.registry.ModelFamily` / :class:`~.engine.FamilyScorer` —
    the fleet-serving pair: per-tenant versioned deploy/rollback over ONE
    shared design signature, scored as mixed ``(tenant, x)`` batches in a
    single gather-score dispatch (with sticky A/B splits and shadow
    scoring in the same executable).
  * :class:`~.batching.MicroBatcher` — bounded admission queue coalescing
    concurrent requests into micro-batches under a latency budget
    (``BatchPolicy``), with typed :class:`~..robust.retry.Overloaded`
    backpressure and per-model p50/p99 latency + throughput metrics.

Serving is numerics-NEUTRAL: a served prediction is bit-identical to
``sg.predict`` on the same rows (PARITY.md; test-enforced across every
padding bucket), because serving runs the same jitted kernel as offline
scoring and every kernel output is row-local.
"""

from .batching import BatchPolicy, MicroBatcher
from .engine import FamilyScorer, Scorer, family_score_cache_size
from .registry import ModelFamily, ModelRegistry

__all__ = ["BatchPolicy", "FamilyScorer", "MicroBatcher", "ModelFamily",
           "ModelRegistry", "Scorer", "family_score_cache_size"]
