"""Micro-batching with latency SLOs — now a thin shim over the async engine.

:class:`MicroBatcher` was the original serving front end: a bounded
admission queue feeding ONE background scoring thread that coalesced
compatible requests into padded-bucket kernel calls.  The continuous-
batching engine (:mod:`.async_engine`) generalizes every part of that —
per-tenant queues under deficit round-robin instead of one FIFO, a free-
replica scheduler instead of one thread, batch formation at dispatch time
instead of admission time — so the batcher is now a compatibility wrapper
that maps its policy onto an :class:`~.async_engine.AsyncEngine` with one
implicit tenant.  One scheduler implementation, two APIs.

The contracts callers (and tests) rely on are unchanged:

  * Coalescing is BIT-NEUTRAL: the training-``Terms`` transform and every
    kernel output are row-local, so scoring a concatenated batch and
    slicing equals scoring each request alone — which in turn equals
    ``sg.predict``.  Only requests with the same column signature coalesce
    (same feature names, same offset-ness); mixed shapes just run in
    separate calls.
  * In-order error propagation: results and failures are delivered to
    each request's future in admission order; a failing micro-batch fails
    every member request (they shared the call), later requests are
    unaffected.
  * Backpressure is TYPED: when the queue is full, ``submit`` raises
    :class:`~..robust.retry.Overloaded` — a ``TransientSourceError``
    subclass, so a client-side ``RetryPolicy`` classifies it transient and
    backs off, exactly like a flaky chunk source at fit time.

Per-model SLO telemetry lands in ``obs.metrics`` under the same names as
before (the engine emits them): a request-latency histogram
(``serve.<name>.latency_s``), a throughput gauge
(``serve.<name>.rows_per_s``), and counters for batches/rows/overloads.
"""

from __future__ import annotations

import dataclasses

from .async_engine import AsyncEngine, EnginePolicy

__all__ = ["BatchPolicy", "MicroBatcher"]


@dataclasses.dataclass(frozen=True)
class BatchPolicy:
    """The admission-control knobs.

    ``max_batch``: row cap per micro-batch (one kernel call); a single
    request larger than this still runs, alone.  ``max_delay_ms``: how long
    the scoring thread may hold an admitted request open waiting for
    company — the latency half of the SLO.  ``max_queue``: queued-request
    cap beyond which ``submit`` raises :class:`Overloaded` (backpressure).
    """

    max_batch: int = 256
    max_delay_ms: float = 2.0
    max_queue: int = 1024

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.max_delay_ms < 0:
            raise ValueError(
                f"max_delay_ms must be >= 0, got {self.max_delay_ms}")
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {self.max_queue}")

    def as_engine_policy(self) -> EnginePolicy:
        """The equivalent continuous-batching policy: same row cap, same
        hold-open window, same queue bound; fairness quantum is moot with
        one implicit tenant."""
        return EnginePolicy(max_batch=self.max_batch,
                            max_wait_ms=self.max_delay_ms,
                            max_queue=self.max_queue,
                            quantum=self.max_batch)


class MicroBatcher:
    """Admission queue + micro-batch coalescing over one :class:`Scorer`
    (an :class:`~.async_engine.AsyncEngine` with a single implicit tenant).

    ``submit`` returns a ``concurrent.futures.Future`` immediately;
    ``score`` is the blocking convenience.  Use as a context manager or
    call ``close()`` — pending requests drain before the engine exits.
    """

    def __init__(self, scorer, policy: BatchPolicy | None = None, *,
                 metrics=None, name: str | None = None):
        self.scorer = scorer
        self.policy = policy if policy is not None else BatchPolicy()
        self.metrics = metrics if metrics is not None else scorer.metrics
        self.name = name if name is not None else scorer.name
        self._engine = AsyncEngine(scorer, self.policy.as_engine_policy(),
                                   metrics=self.metrics, name=self.name)

    def submit(self, data, *, offset=None):
        """Enqueue one scoring request; returns its Future.

        Raises :class:`Overloaded` (transient, retryable) when
        ``policy.max_queue`` requests are already waiting, and
        ``RuntimeError`` after ``close()``.
        """
        return self._engine.submit(data, offset=offset)

    def score(self, data, *, offset=None, timeout: float | None = None):
        """Blocking submit: the served result (or the served exception)."""
        return self._engine.score(data, offset=offset, timeout=timeout)

    def close(self) -> None:
        """Drain pending requests, then stop the engine."""
        self._engine.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
