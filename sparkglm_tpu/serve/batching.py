"""Micro-batching with latency SLOs: coalesce concurrent scoring requests.

One padded-bucket kernel call amortizes its dispatch overhead over every
row in the batch, so serving throughput wants BIG calls while serving
latency wants IMMEDIATE ones.  The :class:`MicroBatcher` sits between: a
bounded admission queue feeds a single background scoring thread that
coalesces compatible queued requests into one micro-batch, capped by
``BatchPolicy.max_batch`` rows, waiting at most ``max_delay_ms`` past the
first request's arrival — the classic latency/throughput knob pair.

Correctness contracts (all test-enforced):

  * Coalescing is BIT-NEUTRAL: the training-``Terms`` transform and every
    kernel output are row-local, so scoring a concatenated batch and
    slicing equals scoring each request alone — which in turn equals
    ``sg.predict``.  Only requests with the same column signature coalesce
    (same feature names, same offset-ness); mixed shapes just run in
    separate calls.
  * In-order error propagation, the ``data/pipeline.py`` discipline: results
    and failures are delivered to each request's future in admission order;
    a failing micro-batch fails every member request (they shared the
    call), later requests are unaffected.
  * Backpressure is TYPED: when the queue is full, ``submit`` raises
    :class:`~..robust.retry.Overloaded` — a ``TransientSourceError``
    subclass, so a client-side ``RetryPolicy`` classifies it transient and
    backs off, exactly like a flaky chunk source at fit time.

Per-model SLO telemetry lands in ``obs.metrics``: a request-latency
histogram (``serve.<name>.latency_s`` — submit to delivery, the number
p50/p99 SLOs are written against), a throughput gauge
(``serve.<name>.rows_per_s``), and counters for requests/rows/batches/
overloads.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from concurrent.futures import Future

import numpy as np

from ..data.frame import as_columns
from ..robust.retry import Overloaded

__all__ = ["BatchPolicy", "MicroBatcher"]


@dataclasses.dataclass(frozen=True)
class BatchPolicy:
    """The admission-control knobs.

    ``max_batch``: row cap per micro-batch (one kernel call); a single
    request larger than this still runs, alone.  ``max_delay_ms``: how long
    the scoring thread may hold an admitted request open waiting for
    company — the latency half of the SLO.  ``max_queue``: queued-request
    cap beyond which ``submit`` raises :class:`Overloaded` (backpressure).
    """

    max_batch: int = 256
    max_delay_ms: float = 2.0
    max_queue: int = 1024

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.max_delay_ms < 0:
            raise ValueError(
                f"max_delay_ms must be >= 0, got {self.max_delay_ms}")
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {self.max_queue}")


@dataclasses.dataclass
class _Request:
    data: object          # normalized columns dict, or an (n, p) design
    offset: object        # explicit offset array or None
    n: int
    key: tuple            # coalescing signature
    future: Future
    t_submit: float


def _signature(data, offset) -> tuple:
    """Only identically-shaped requests coalesce: same feature columns (or
    same design width) and same explicit-offset-ness.  Model-side offset
    recovery is per-column-name, hence covered by the column signature."""
    if isinstance(data, np.ndarray):
        return ("design", data.shape[1], offset is not None)
    return ("cols",) + tuple(sorted(data)) + (offset is not None,)


def _merge(batch: list[_Request]):
    """Concatenate member requests into one scoring call's input."""
    first = batch[0]
    if len(batch) == 1:
        return first.data, first.offset
    if isinstance(first.data, np.ndarray):
        data = np.concatenate([r.data for r in batch], axis=0)
    else:
        data = {k: np.concatenate([np.asarray(r.data[k]) for r in batch])
                for k in first.data}
    off = (np.concatenate([np.asarray(r.offset, np.float64) for r in batch])
           if first.offset is not None else None)
    return data, off


def _split(res, sizes: list[int]):
    """Slice a batch result back into per-request results (handles the
    se_fit ``(fit, se)`` tuple shape)."""
    edges = np.cumsum([0] + sizes)
    if isinstance(res, tuple):
        return [tuple(part[edges[i]:edges[i + 1]] for part in res)
                for i in range(len(sizes))]
    return [res[edges[i]:edges[i + 1]] for i in range(len(sizes))]


class MicroBatcher:
    """Admission queue + single scoring thread over one :class:`Scorer`.

    ``submit`` returns a ``concurrent.futures.Future`` immediately;
    ``score`` is the blocking convenience.  Use as a context manager or
    call ``close()`` — pending requests drain before the thread exits.
    """

    def __init__(self, scorer, policy: BatchPolicy | None = None, *,
                 metrics=None, name: str | None = None):
        self.scorer = scorer
        self.policy = policy if policy is not None else BatchPolicy()
        self.metrics = metrics if metrics is not None else scorer.metrics
        self.name = name if name is not None else scorer.name
        self._q: collections.deque[_Request] = collections.deque()
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._closed = False
        self._rows_done = 0
        self._t_first = None  # first delivery epoch, for the throughput gauge
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"microbatch:{self.name}")
        self._thread.start()

    # -- client side ---------------------------------------------------------

    def submit(self, data, *, offset=None) -> Future:
        """Enqueue one scoring request; returns its Future.

        Raises :class:`Overloaded` (transient, retryable) when
        ``policy.max_queue`` requests are already waiting, and
        ``RuntimeError`` after ``close()``.
        """
        if isinstance(data, np.ndarray):
            if data.ndim != 2:
                raise ValueError(
                    f"design requests must be 2-D, got shape {data.shape}")
            n = data.shape[0]
        else:
            data = as_columns(data)
            n = len(np.asarray(next(iter(data.values())))) if data else 0
        if n < 1:
            raise ValueError("request must have >= 1 row")
        req = _Request(data=data, offset=offset, n=n,
                       key=_signature(data, offset), future=Future(),
                       t_submit=time.perf_counter())
        with self._nonempty:
            if self._closed:
                raise RuntimeError(f"MicroBatcher {self.name!r} is closed")
            if len(self._q) >= self.policy.max_queue:
                if self.metrics is not None:
                    self.metrics.counter(
                        f"serve.{self.name}.overloaded").inc()
                raise Overloaded(
                    f"serving queue for {self.name!r} is full "
                    f"({self.policy.max_queue} requests waiting); retry "
                    "with backoff")
            self._q.append(req)
            self._nonempty.notify()
        return req.future

    def score(self, data, *, offset=None, timeout: float | None = None):
        """Blocking submit: the served result (or the served exception)."""
        return self.submit(data, offset=offset).result(timeout)

    def close(self) -> None:
        """Drain pending requests, then stop the scoring thread."""
        with self._nonempty:
            self._closed = True
            self._nonempty.notify_all()
        self._thread.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- scoring thread ------------------------------------------------------

    def _loop(self) -> None:
        pol = self.policy
        while True:
            with self._nonempty:
                while not self._q and not self._closed:
                    self._nonempty.wait()
                if not self._q:     # closed and drained
                    return
                first = self._q.popleft()
                batch, rows = [first], first.n
                deadline = first.t_submit + pol.max_delay_ms / 1e3
                # coalesce: take compatible queued requests up to max_batch
                # rows, waiting out the delay window while there is room;
                # an incompatible head request ends the batch (order is
                # preserved — we never skip past it)
                while rows < pol.max_batch:
                    if self._q:
                        nxt = self._q[0]
                        if (nxt.key != first.key
                                or rows + nxt.n > pol.max_batch):
                            break
                        self._q.popleft()
                        batch.append(nxt)
                        rows += nxt.n
                        continue
                    remaining = deadline - time.perf_counter()
                    if self._closed or remaining <= 0:
                        break
                    self._nonempty.wait(timeout=remaining)
            self._run(batch, rows)

    def _run(self, batch: list[_Request], rows: int) -> None:
        try:
            data, off = _merge(batch)
            res = self.scorer.score(data, offset=off)
            parts = _split(res, [r.n for r in batch])
        except BaseException as e:  # noqa: BLE001 — delivered, not swallowed
            # in-order failure delivery: every member shared the call
            for r in batch:
                r.future.set_exception(e)
            return
        now = time.perf_counter()
        if self._t_first is None:
            self._t_first = now
        self._rows_done += rows
        for r, part in zip(batch, parts):
            r.future.set_result(part)
            if self.metrics is not None:
                self.metrics.histogram(
                    f"serve.{self.name}.latency_s").observe(now - r.t_submit)
        if self.metrics is not None:
            self.metrics.counter(f"serve.{self.name}.batches").inc()
            self.metrics.counter(
                f"serve.{self.name}.batched_rows").inc(rows)
            elapsed = now - self._t_first
            if elapsed > 0:
                self.metrics.gauge(f"serve.{self.name}.rows_per_s").set(
                    self._rows_done / elapsed)
