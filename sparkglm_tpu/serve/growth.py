"""Zero-downtime tenant-axis growth for a served :class:`ModelFamily`.

The serving kernel (serve/engine.py ``_family_score_kernel``) keys its
compiled executables on the SHAPES of the coefficient tables, and the
tables are sized by the tenant count — so naively registering a tenant
that crosses the power-of-2 tenant bucket would recompile every replica
on the next hot-path call, exactly the jank a multi-tenant fleet cannot
afford under live traffic.  :class:`FamilyGrowth` sequences growth so
the hot path never pays:

  1. **warm** — compile the next tenant-bucket's executables into the
     process-wide jit cache via
     :meth:`ReplicatedScorer.prewarm_tenant_axis` on every scorer that
     serves the family (explicitly attached ones plus the family's own
     ``replicated_scorer()`` cache).  Traffic keeps flowing on the old
     tables the whole time; prewarm compiles run on zero-filled decoys.
  2. **swap** — register + deploy the new tenants.  With an
     :class:`OnlineLoop` attached this routes through
     :meth:`OnlineLoop.grow`, which migrates suffstats, drift windows
     and retained-row rings by label in the same step (and snapshots if
     a journal is attached); without a loop it registers directly into
     the family.  Either way the family's generation counter bumps, so
     every generation-following scorer (``AsyncEngine.refresh``,
     ``FamilyScorer`` cache) picks up the grown tables on its next
     batch — and because step 1 already compiled those shapes, the
     pickup is a cache HIT, measured as ``compiles == 0`` by the
     steady-state counters the chaos test asserts on.

Within-bucket growth (tenant count stays under the current power-of-2
bucket) needs no warm at all: the padded table shapes do not change, so
step 1 is a no-op and the swap is free by construction.
"""

from __future__ import annotations

import time

from .engine import tenant_bucket

__all__ = ["FamilyGrowth"]


class FamilyGrowth:
    """Warm-then-swap growth coordinator (module doc).

    Args:
      family: the :class:`ModelFamily` to grow.
      scorers: extra :class:`ReplicatedScorer` instances serving this
        family that are not in the family's own ``replicated_scorer()``
        cache (e.g. per-engine scorers built by serve/pool.py).  The
        cache's scorers are always discovered automatically.
      loop: an :class:`OnlineLoop` over the same family, or None.  When
        given, the swap routes through :meth:`OnlineLoop.grow` so the
        learning plane migrates in the same step as the serving plane.
      tracer: an ``obs/trace.FitTracer`` (or None) for the
        ``growth_start`` / ``growth_warm`` / ``growth_end`` /
        ``growth`` events.
      telemetry: a :class:`~..obs.Telemetry` — shorthand for
        ``tracer=telemetry.tracer`` (an explicit ``tracer=`` wins).
    """

    def __init__(self, family, *, scorers=(), loop=None, tracer=None,
                 telemetry=None):
        if loop is not None and loop.family is not family:
            raise ValueError("loop must wrap the same ModelFamily")
        self.family = family
        self.scorers = tuple(scorers)
        self.loop = loop
        self.telemetry = telemetry
        if tracer is None and telemetry is not None:
            tracer = telemetry.tracer
        self.tracer = tracer

    def _emit(self, event: str, **fields) -> None:
        if self.tracer is not None:
            self.tracer.emit(event, **fields)

    def _all_scorers(self) -> tuple:
        seen, out = set(), []
        for sc in (*self.scorers, *self.family._replicated.values()):
            if id(sc) not in seen:
                seen.add(id(sc))
                out.append(sc)
        return tuple(out)

    def grow(self, models: dict) -> dict:
        """Grow the family by ``{tenant: model}`` with zero downtime.

        Returns a report dict: ``added`` (sorted new tenants),
        ``tenants`` (total after), ``crossed`` (whether the tenant
        bucket grew), ``table_rows`` (padded tenant rows after),
        ``prewarm`` (per-scorer ``prewarm_tenant_axis`` reports —
        compiles here are the price paid OFF the hot path),
        ``warm_s`` / ``swap_s`` / ``total_s`` wall times.
        """
        new = {str(t): m for t, m in models.items()}
        if not new:
            raise ValueError("no tenants to grow by")
        dup = sorted(set(new) & set(self.family.tenants()))
        if dup:
            raise ValueError(
                f"tenants already in the family: {dup[:4]}"
                f"{'...' if len(dup) > 4 else ''}")
        before = len(self.family)
        target = before + len(new)
        crossed = tenant_bucket(target) > tenant_bucket(before)
        t0 = time.perf_counter()
        self._emit("growth_start", adding=len(new), tenants=before,
                   crossed=crossed)

        # 1. warm: compile next-bucket executables while traffic flows on
        # the old tables.  Within-bucket growth skips straight to swap.
        prewarm = []
        if crossed:
            for sc in self._all_scorers():
                rep = sc.prewarm_tenant_axis(target)
                prewarm.append(rep)
                self._emit("growth_warm", table_rows=rep["table_rows"],
                           buckets=rep["buckets"],
                           compiles=rep["compiles"],
                           seconds=round(rep["seconds"], 6))
        warm_s = time.perf_counter() - t0

        # 2. swap: one registration step; the generation bump publishes
        # the grown tables to every generation-following scorer.
        t1 = time.perf_counter()
        if self.loop is not None:
            self.loop.grow(new)
        else:
            for t in sorted(new):
                self.family.register(t, new[t])  # v1 auto-deploys
        swap_s = time.perf_counter() - t1

        report = dict(
            added=tuple(sorted(new)), tenants=len(self.family),
            crossed=crossed, table_rows=tenant_bucket(len(self.family)),
            prewarm=tuple(prewarm), warm_s=warm_s, swap_s=swap_s,
            total_s=time.perf_counter() - t0)
        self._emit("growth_end", tenants=report["tenants"],
                   crossed=crossed,
                   prewarm_compiles=sum(r["compiles"] for r in prewarm),
                   total_s=round(report["total_s"], 6))
        # one consolidated event for dashboards/aggregation: the whole
        # episode's phase timings on a single line
        self._emit("growth", added=len(new), tenants=report["tenants"],
                   crossed=crossed, warm_s=round(warm_s, 6),
                   swap_s=round(swap_s, 6),
                   total_s=round(report["total_s"], 6),
                   prewarm_compiles=sum(r["compiles"] for r in prewarm))
        return report
