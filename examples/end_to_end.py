"""End-to-end tour of sparkglm-tpu — every major capability in one script.

Run anywhere (CPU mesh or TPU):

    python examples/end_to_end.py

On CPU it forces an 8-virtual-device mesh so the sharded paths are real.
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# Default to a local 8-device CPU mesh unless the caller asked for TPU
# (EXAMPLE_TPU=1).  Checking jax.default_backend() first would INITIALIZE
# a backend — on a machine with a broken accelerator plugin that can hang.
if os.environ.get("EXAMPLE_TPU") != "1":
    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)
    except RuntimeError:
        pass  # backend already initialized by the environment

import numpy as np

import sparkglm_tpu as sg

rng = np.random.default_rng(7)
n = 20_000

# ---------------------------------------------------------------------------
# 1. A realistic model frame: factors, transforms, splines, offsets, weights
# ---------------------------------------------------------------------------
data = {
    "claims":  None,                                   # filled below
    "age":     rng.uniform(18, 80, n),
    "veh":     np.array(["car", "moto", "truck"])[rng.integers(0, 3, n)],
    "dens":    rng.uniform(10, 5000, n),               # population density
    "expo":    rng.uniform(0.1, 2.0, n),               # exposure years
    "w":       rng.uniform(0.5, 2.0, n),               # prior weights
}
eff = {"car": 0.0, "moto": 0.6, "truck": 0.25}
eta = (-2.2 + 0.015 * (data["age"] - 45) + 0.22 * np.log(data["dens"] / 100)
       + np.vectorize(eff.get)(data["veh"]) + np.log(data["expo"]))
data["claims"] = rng.poisson(np.exp(eta)).astype(float)
data["log_expo"] = np.log(data["expo"])

# ---------------------------------------------------------------------------
# 2. Fit: formula front-end, R semantics end to end
# ---------------------------------------------------------------------------
mesh = sg.make_mesh()                                  # all devices, "data" axis
m = sg.glm("claims ~ age + log(dens) + veh + offset(log_expo)", data,
           family="poisson", weights="w", mesh=mesh)
print(m.summary())

# splines + interactions fit the same way
m_flex = sg.glm("claims ~ ns(age, 4) + log(dens) * veh + offset(log_expo)",
                data, family="poisson", weights="w", mesh=mesh)

# ---------------------------------------------------------------------------
# 3. Inference verbs
# ---------------------------------------------------------------------------
print(sg.anova(m, m_flex, test="Chisq"))               # analysis of deviance
print(sg.drop1(m, data, test="Chisq"))                 # single-term deletions
ci = sg.confint_profile(m, data, which=["age"])        # profile likelihood
print("profile CI for age:", np.round(ci[m.xnames.index("age")], 5))
print("AIC", round(m.aic, 2), " BIC", round(m.bic(), 2))

# per-term link-scale decomposition (R's predict type="terms")
tp = sg.predict(m, data, type="terms")
print("terms:", tp.columns, " constant:", round(tp.constant, 4))

# single-term additions and AIC-stepwise selection (R's add1/step; the
# hierarchy gate admits an interaction only once its margins are in)
print(sg.add1(m, "~ . + age:veh", data, test="Chisq"))
sel = sg.step(sg.glm("claims ~ offset(log_expo)", data, family="poisson",
                     weights="w"),
              data, scope="~ age + log(dens) + veh")
print("step selected:", sel.formula)

# single-model sequential anova — R's anova(fit): terms added first to
# last (models don't retain data, so pass it back in)
print(sg.anova(m, data, test="Chisq"))

# case-deletion influence, digit-for-digit R's influence.glm (deviance
# residuals through the downdate) — the fit-time offset() column travels
# with the model and is recovered from the data automatically
infl = sg.dffits(m, data, data["claims"], weights=data["w"])
print("max |dffits| row:", int(np.argmax(np.abs(infl))))
im = sg.influence_measures(m, data, data["claims"], weights=data["w"])
flagged = np.flatnonzero(im.is_inf.any(axis=1))
print("influence.measures flags", len(flagged), "rows;",
      im.columns[-4:], "columns")
print("rstudent extremes:",
      np.round(np.sort(sg.rstudent(m, data, data["claims"],
                                   weights=data["w"]))[[0, -1]], 3))

# parametric bootstrap material: R's simulate() draws new responses from
# the fitted family at the fitted values; the fit-time by-name weights
# column is auto-recovered, and (exactly like R's poisson()$simulate)
# non-unit prior weights draw a warning and are ignored for poisson
import warnings as _w
with _w.catch_warnings():
    _w.simplefilter("ignore")
    sims = sg.simulate(m, data, nsim=3, seed=0)
print("simulate:", sims.shape, "col means", np.round(sims.mean(0), 3))

# ---------------------------------------------------------------------------
# 4. Scoring — host, and sharded over the mesh (the reference's
#    executor-side predictMultiple, as one SPMD pass)
# ---------------------------------------------------------------------------
new = {k: v[:100] for k, v in data.items()}
mu_host = sg.predict(m, new)                           # recovers offset column
mu_mesh = sg.predict(m, new, mesh=mesh)
assert np.allclose(mu_host, mu_mesh, rtol=1e-5)

# ---------------------------------------------------------------------------
# 5. Persistence and update
# ---------------------------------------------------------------------------
with tempfile.TemporaryDirectory() as td:
    path = os.path.join(td, "model.npz")
    m.save(path)
    m2 = sg.load_model(path)
    assert np.allclose(sg.predict(m2, new), mu_host)
m3 = sg.update(m, "~ . - veh", data)                   # R's update()
print("updated:", m3.formula)

# ---------------------------------------------------------------------------
# 6. Out-of-core: fit straight from a CSV, then run the verbs on the FILE
# ---------------------------------------------------------------------------
with tempfile.TemporaryDirectory() as td:
    csv = os.path.join(td, "big.csv")
    cols = ["claims", "age", "dens", "veh", "log_expo", "w"]
    with open(csv, "w") as f:
        f.write(",".join(cols) + "\n")
        for i in range(n):
            f.write(",".join(str(data[c][i]) for c in cols) + "\n")
    big = sg.glm_from_csv("claims ~ age + log(dens) + veh + offset(log_expo)",
                          csv, family="poisson", weights="w",
                          chunk_bytes=1 << 18)
    assert np.allclose(big.coefficients, m.coefficients, atol=1e-4)
    t = sg.drop1(big, csv, test="Chisq")               # verbs on the path
    print("from-CSV drop1 rows:", t.row_names)

# ---------------------------------------------------------------------------
# 7. Checkpoint / resume (the explicit replacement for lineage recovery)
# ---------------------------------------------------------------------------
ckpt = {}
m4 = sg.glm("claims ~ age + veh + offset(log_expo)", data, family="poisson",
            checkpoint_every=2,
            on_iteration=lambda it, b, d: ckpt.update(beta=b, it=it))
resumed = sg.glm("claims ~ age + veh + offset(log_expo)", data,
                 family="poisson", beta0=ckpt["beta"])
assert resumed.iterations <= 2
print(f"checkpointed at iter {ckpt['it']}; resume converged in "
      f"{resumed.iterations} iteration(s)")

# ---------------------------------------------------------------------------
# 8. Columnar + JSON ingestion, and out-of-core scoring (r4)
# ---------------------------------------------------------------------------
import json as json_mod

with tempfile.TemporaryDirectory() as td:
    cols = ["claims", "age", "dens", "veh", "log_expo", "w"]
    # the same model frame as NDJSON — the reference's own fixture format
    nd = os.path.join(td, "big.jsonl")
    with open(nd, "w") as f:
        for i in range(n):
            f.write(json_mod.dumps(
                {c: (float(data[c][i])
                     if np.issubdtype(data[c].dtype, np.number)
                     else str(data[c][i])) for c in cols}) + "\n")
    mj = sg.glm_from_json("claims ~ age + log(dens) + veh + offset(log_expo)",
                          nd, family="poisson", weights="w",
                          chunk_bytes=1 << 18)
    assert np.allclose(mj.coefficients, m.coefficients, atol=1e-4)

    # and as Parquet (row-group-band sharding; column-pruned reads)
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq
        pqp = os.path.join(td, "big.parquet")
        pq.write_table(pa.table({c: list(data[c]) for c in cols}), pqp,
                       row_group_size=4096)
        mp = sg.glm_from_parquet(
            "claims ~ age + log(dens) + veh + offset(log_expo)", pqp,
            family="poisson", weights="w")
        assert np.allclose(mp.coefficients, m.coefficients, atol=1e-4)
        # out-of-core scoring: the file streams through the training Terms,
        # bit-identical to loading it whole; out_path streams to disk
        scores = sg.predict(m, pqp)
        out_csv = os.path.join(td, "scored.csv")
        sg.predict(m, pqp, out_path=out_csv)
        print("scored", len(np.asarray(scores)), "rows from parquet; "
              "fit/se streamed to", os.path.basename(out_csv))
    except ImportError:
        print("pyarrow not installed; parquet leg skipped")

# from-file lm with offsets prints R's Residuals block by default, and
# ill-conditioned out-of-core fits auto-escalate to the chunked CSNE polish
print("\nend-to-end tour complete.")
